"""Pipelined cold-staging microbench: serial vs pipelined, host-tier refill.

The cold q3-shaped staging path (TPC-H Q3's three scans with phase-1
dynamic-filter domains applied, the exact shape BENCH_r05 measured at
22.7 s staging for q3_sf10) run three ways through the staging engine
(trino_tpu/exec/staging.py):

- **serial** — ``staging_parallelism=1``: the sequential
  scan→decode→transfer loop (the pre-pipeline code path, preserved as the
  engine's width-1 degenerate case);
- **pipelined** — the fan-out over the shared staging pool with
  double-buffered blocked transfer; staged arrays are asserted
  BIT-IDENTICAL to the serial arm's;
- **host refill** — the HBM tier is evicted while the host-RAM columnar
  cache stays warm: staging must rebuild the device pages with ZERO
  connector scan calls, the cold-path tax an eviction used to re-pay.

Caches (gencache, host tier, HBM tier) are cleared between the cold arms
so each pays the full connector scan+decode.

Writes ``STAGING_r01.json`` (folded into TRAJECTORY.json by
``tools/bench_trend.py``). ``--check`` runs a quick small-schema pass as
the tier-1 regression gate (tests/test_staging.py::test_staging_bench_check):
bit-identity, zero-connector-call refill above the speedup floor, and the
pipelined arm never slower than serial beyond tolerance. The ≥2x
pipelined-over-serial acceptance bound is asserted only on multi-core
boxes — like ``microbench/qps.py``'s documented single-core carve-out, a
1-vCPU box timeshares the scan threads and can only prove bit-identity,
refill, and not-slower there (the overlap fraction is recorded either
way; the hardware round re-measures on a real host).

Run: python microbench/staging.py [tpch_schema]   (default sf2)
     python microbench/staging.py --check         (quick gate, sf0.2)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# self-locate the repo (PYTHONPATH must not be used on TPU runs)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MIN_SPEEDUP = 2.0          # pipelined vs serial, multi-core acceptance
MIN_REFILL_SPEEDUP = 2.5   # host refill vs cold connector re-scan (gate)
FULL_REFILL_SPEEDUP = 5.0  # the r01 acceptance bound at sf>=2
MAX_SLOWDOWN = 1.3         # pipelined must never exceed serial by this

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""


def _clear_caches():
    from trino_tpu.connector.tpch import generator
    from trino_tpu.devcache import DEVICE_CACHE, HOST_CACHE

    DEVICE_CACHE.invalidate_all()
    HOST_CACHE.invalidate_all()
    generator._gen_cache.clear()


def _session(schema: str, parallelism: int):
    from trino_tpu.client.session import Session

    return Session({"catalog": "tpch", "schema": schema,
                    "device_cache_enabled": True,
                    "staging_parallelism": parallelism})


def _stage_q3(session, count_scans=False):
    """Stage Q3's three scans exactly as the compiled tier would (phase-1
    dynamic-filter domains applied host-side), through the pipelined
    engine. Returns (pages by table, staging wall seconds, profiles,
    connector scan calls)."""
    from trino_tpu.exec import host_eval, staging
    from trino_tpu.exec.executor import (
        apply_dynamic_domains, dynamic_domain_map, scan_constraint_with)
    from trino_tpu.exec.query import plan_sql
    from trino_tpu.sql.planner import plan as P

    root = plan_sql(session, Q3)
    dyn = host_eval.resolve_dynamic_filters(session, root)
    scans = [n for n in P.walk_plan(root) if isinstance(n, P.TableScanNode)]
    conn = session.catalogs["tpch"]
    calls = [0]
    if count_scans:
        inner = conn.scan

        def counted(split, columns, constraint=None):
            calls[0] += 1
            return inner(split, columns, constraint=constraint)

        conn.scan = counted
    pages, profiles = {}, {}
    t0 = time.perf_counter()
    try:
        for node in scans:
            constraint = scan_constraint_with(node, dyn)
            target = staging.target_split_count(
                session, conn, node.schema, node.table)
            splits = conn.get_splits(
                node.schema, node.table, target, constraint=constraint,
                handle=node.table_handle)

            def prune(datas, node=node):
                return apply_dynamic_domains(node, dyn, datas)

            page, _rows, prof = staging.staged_scan_page(
                session, node, conn, splits, constraint, prune=prune,
                applied_domains=dynamic_domain_map(node, dyn))
            for c in page.columns:
                c.values.block_until_ready()
            pages[node.table] = page
            profiles[node.table] = prof
    finally:
        if count_scans:
            conn.scan = type(conn).scan.__get__(conn)
    return pages, time.perf_counter() - t0, profiles, calls[0]


def _page_arrays(page):
    out = []
    for c in page.columns:
        out.append(np.asarray(c.values))
        out.append(None if c.nulls is None else np.asarray(c.nulls))
    return out


def _assert_identical(a_pages, b_pages, label):
    for table in a_pages:
        for x, y in zip(_page_arrays(a_pages[table]),
                        _page_arrays(b_pages[table])):
            if x is None or y is None:
                assert x is None and y is None, (label, table)
                continue
            assert x.dtype == y.dtype and x.shape == y.shape, (
                label, table, x.dtype, y.dtype, x.shape, y.shape)
            assert np.array_equal(x, y), f"{label}: {table} diverged"


def run(schema: str, check_mode: bool) -> dict:
    cores = os.cpu_count() or 1

    _clear_caches()
    serial_pages, serial_s, _prof, _ = _stage_q3(_session(schema, 1))

    _clear_caches()
    pipe_session = _session(schema, 0)  # auto width
    pipe_pages, pipelined_s, profiles, _ = _stage_q3(pipe_session)
    _assert_identical(serial_pages, pipe_pages, "pipelined-vs-serial")

    splits = sum(p.splits for p in profiles.values())
    fanout = sum(p.fanout_wall_s for p in profiles.values())
    busy = sum(p.scan_s + p.prune_s for p in profiles.values())
    overlap = round(busy / fanout, 3) if fanout else 0.0

    # host refill: evict the HBM tier only; the warm host tier must
    # rebuild the device pages without a single connector scan call
    from trino_tpu.devcache import DEVICE_CACHE, HOST_CACHE

    DEVICE_CACHE.invalidate_all()
    assert HOST_CACHE.cached_bytes() > 0, "host tier not filled"
    refill_pages, refill_s, _p, refill_scans = _stage_q3(
        pipe_session, count_scans=True)
    _assert_identical(pipe_pages, refill_pages, "refill-vs-cold")

    report = {
        "round": 1,
        "tpch_schema": schema,
        "cores": cores,
        "single_core": cores == 1,
        "splits": int(splits),
        "serial_s": round(serial_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "pipelined_speedup": round(serial_s / pipelined_s, 4)
        if pipelined_s else 0.0,
        "overlap_fraction": overlap,
        "host_refill_s": round(refill_s, 4),
        "refill_speedup": round(pipelined_s / refill_s, 4)
        if refill_s else 0.0,
        "refill_connector_scans": int(refill_scans),
        "host_cache_bytes": HOST_CACHE.cached_bytes(),
        "min_speedup": MIN_SPEEDUP,
        "min_refill_speedup": (MIN_REFILL_SPEEDUP if check_mode
                               else FULL_REFILL_SPEEDUP),
    }

    assert refill_scans == 0, "host refill touched the connector"
    bound = MIN_REFILL_SPEEDUP if check_mode else FULL_REFILL_SPEEDUP
    assert report["refill_speedup"] >= bound, (
        f"host refill {refill_s:.3f}s not {bound}x faster than cold "
        f"{pipelined_s:.3f}s")
    assert pipelined_s <= serial_s * MAX_SLOWDOWN, (
        f"pipelined {pipelined_s:.3f}s slower than serial {serial_s:.3f}s")
    if cores >= 4:
        assert report["pipelined_speedup"] >= MIN_SPEEDUP, (
            f"pipelined speedup {report['pipelined_speedup']} < "
            f"{MIN_SPEEDUP}x on a {cores}-core box")
    return report


def main() -> None:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    check_mode = "--check" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    schema = args[0] if args else ("sf0.2" if check_mode else "sf2")
    report = run(schema, check_mode)
    print(json.dumps(report, indent=2))
    if check_mode:
        print(f"staging-check ok: serial {report['serial_s']}s, "
              f"pipelined {report['pipelined_s']}s, refill "
              f"{report['host_refill_s']}s over {report['splits']} splits")
        return
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "STAGING_r01.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}: cold {report['pipelined_s']}s "
          f"({report['pipelined_speedup']}x vs serial, overlap "
          f"{report['overlap_fraction']}), host refill "
          f"{report['host_refill_s']}s ({report['refill_speedup']}x)")


if __name__ == "__main__":
    main()
