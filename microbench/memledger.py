"""Memory-ledger microbench: the cluster footprint trajectory.

Every BENCH_r*/QPS_r* round tracks throughput; this bench tracks what
the same serving shape COSTS in memory, so a footprint regression (a
leaked cache tier, an unbounded ring, a staging buffer that stopped
releasing) gates exactly like a throughput regression. It boots a real
coordinator + N workers in one process (the DistributedQueryRunner
idiom), drives the TPC-H q3 shape with the device cache on, and reads
the cluster memory ledger's own surfaces — the bench measures the
instrumentation the PR ships:

- **peak_rss_mb** — process peak RSS across the run, sampled from
  ``/proc`` (obs/metrics.current_rss_bytes) every round; in-process the
  coordinator and workers share it, on a real deployment each node's
  announce payload carries its own ``rssBytes``;
- **announced_rss_mb** — the largest worker-announced RSS the
  coordinator saw (the ``system.runtime.nodes``-adjacent path);
- **device_pool_peak_mb** — the device pool's high-water mark from the
  ledger's watermark series (``MEMORY_LEDGER.pool_peaks``);
- **attribution_fraction** — from ``system.runtime.memory``: named-owner
  bytes / the ``total`` watermark row, per device pool at peak — the
  >= 95% acceptance criterion as a trended metric (direction up).

Writes ``MEMLEDGER_r01.json`` (folded into TRAJECTORY.json by
``tools/bench_trend.py``; RSS/pool peaks gate direction=down,
attribution direction=up). ``--check`` is the tiny-schema quick pass.

Run:    python microbench/memledger.py [tpch_schema] [--workers W]
Check:  python microbench/memledger.py --check
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MIN_ATTRIBUTION = 0.95  # the ISSUE acceptance bound
ROUNDS = 5              # q3 repeats (cold round 1, warm rounds after)

Q3_SQL = """
select l_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate, l_orderkey limit 10
"""


def _attribution(rows) -> float:
    """Coverage from system.runtime.memory rows: named-owner bytes over
    the per-(node, pool) ``total`` watermark, device pool only, summed
    across nodes. No tracked bytes at all reads as full coverage."""
    named: dict = {}
    totals: dict = {}
    for node_id, pool, owner, nbytes, _peak, _events in rows:
        if pool != "device":
            continue
        if owner == "total":
            totals[node_id] = totals.get(node_id, 0) + int(nbytes)
        else:
            named[node_id] = named.get(node_id, 0) + int(nbytes)
    total = sum(totals.values())
    if total <= 0:
        return 1.0
    return min(1.0, sum(named.get(n, 0) for n in totals) / total)


def run(schema: str, workers: int) -> dict:
    from trino_tpu.client import dbapi
    from trino_tpu.obs import metrics as M
    from trino_tpu.obs.memledger import MEMORY_LEDGER
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    fleet = [WorkerServer(coordinator_url=coord.base_url,
                          node_id=f"mem{i}") for i in range(workers)]
    for w in fleet:
        w.start()
    assert coord.registry.wait_for_workers(workers, timeout=30.0)
    try:
        cur = dbapi.connect(
            coordinator_url=coord.base_url, catalog="tpch", schema=schema,
            device_cache_enabled="true").cursor()
        peak_rss = 0
        wall = []
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            cur.execute(Q3_SQL)
            wall.append(time.perf_counter() - t0)
            rss = M.current_rss_bytes()
            if rss:
                peak_rss = max(peak_rss, rss)
        # let the announce loop deliver the post-run owner rows (0.5 s
        # cadence) before reading the coordinator-side table
        time.sleep(1.5)
        cur.execute("select node_id, pool, owner, bytes, peak_bytes, "
                    "events from system.runtime.memory")
        mem_rows = cur.fetchall()
        announced = max(
            (int(i.get("rssBytes") or 0)
             for i in coord.cluster_memory._nodes.values()), default=0)
        pool_peaks = MEMORY_LEDGER.pool_peaks()
        return {
            "round": 1,
            "tpch_schema": schema,
            "workers": workers,
            "q3_rounds": ROUNDS,
            "warm_q3_seconds": round(min(wall), 4),
            "peak_rss_mb": round(peak_rss / 2**20, 1),
            "announced_rss_mb": round(announced / 2**20, 1),
            "device_pool_peak_mb": round(
                int(pool_peaks.get("device") or 0) / 2**20, 3),
            "host_pool_peak_mb": round(
                int(pool_peaks.get("host") or 0) / 2**20, 3),
            "attribution_fraction": round(_attribution(mem_rows), 4),
            "memory_rows": len(mem_rows),
        }
    finally:
        for w in fleet:
            w.stop()
        coord.stop()


def main() -> None:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    check_mode = "--check" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    schema = args[0] if args else ("tiny" if check_mode else "sf1")
    report = run(schema, workers=2)
    print(json.dumps(report, indent=2))
    assert report["memory_rows"] > 0, "system.runtime.memory came up empty"
    assert report["attribution_fraction"] >= MIN_ATTRIBUTION, (
        f"device-pool attribution {report['attribution_fraction']} below "
        f"the {MIN_ATTRIBUTION} acceptance bound")
    if check_mode:
        print(f"memledger-check ok: rss {report['peak_rss_mb']}MB, "
              f"device pool {report['device_pool_peak_mb']}MB, "
              f"attribution {report['attribution_fraction']}")
        return
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MEMLEDGER_r01.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}: peak rss {report['peak_rss_mb']}MB, "
          f"device pool peak {report['device_pool_peak_mb']}MB, "
          f"attribution {report['attribution_fraction']}")


if __name__ == "__main__":
    main()
