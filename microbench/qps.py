"""QPS microbench: the serving-path trajectory (queries per second).

Every BENCH_r* round measures single-query throughput; heavy traffic is
queries per SECOND. This bench boots a real coordinator + N workers in
one process (the DistributedQueryRunner idiom the test suite uses),
drives C concurrent DBAPI clients over a mixed serving workload, and
measures the two control-plane configurations ISSUE 10 ships —
and, since ISSUE 12's dispatcher/executor split, the CONCURRENCY
SCALING SWEEP: the serving configuration's point mix at client counts
{1, 2, 4, 8, 16, 32} (per-stage disjoint key ranges so the shared
result cache can never flatter a later stage), emitted as
``QPS_r02.json`` and folded into TRAJECTORY.json as the scaling curve.
``--check`` additionally runs the dispatcher scaling gate (see main).

- **serving ON** — prepared point lookups through PREPARE/EXECUTE (the
  parameterized plan caches once; every EXECUTE is bind + run) with the
  short-query fast path enabled (single-stage plans run
  coordinator-local, zero task HTTP round-trips);
- **serving OFF** — the same statements as plain SQL with literals
  substituted client-side, fast path disabled: every request pays
  parse/analyze/plan/optimize + fragment/schedule/exchange.

Workload mix (per client, round-robin):
- ``point``   — prepared point lookup on ``orders`` (the serving shape);
- ``cached``  — a repeated aggregate with the result cache on (HIT path);
- ``uncached``— an aggregate over a shifting predicate (MISS every time).

Emits ``QPS_r02.json`` next to the other bench artifacts: per-config
qps + p50/p95/p99 latency per workload class, the per-path breakdown
(fast-path vs distributed counts from the coordinator's own metrics),
the ON/OFF speedup on the point mix, and the concurrency sweep with
the ISSUE 12 acceptance record.

Run:    python microbench/qps.py [--clients C] [--requests N] [--workers W]
                                 [--sweep 1,2,4,8,16,32]
Check:  python microbench/qps.py --check [--min-speedup X]
        (tier-1 quick mode, small N, CPU-runnable: asserts the serving
        config clears ``min_speedup`` x on the point-lookup mix AND the
        dispatcher scaling gate — QPS at 8 clients strictly above 2
        clients on multi-core boxes; saturation hold on single-core)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POINT_SQL = ("select o_orderkey, o_totalprice, o_orderstatus "
             "from orders where o_orderkey = ?")
CACHED_SQL = ("select o_orderstatus, count(*), sum(o_totalprice) "
              "from orders group by o_orderstatus order by o_orderstatus")
UNCACHED_SQL = ("select count(*), max(o_totalprice) from orders "
                "where o_orderkey > {k}")

# Point keys are UNIQUE per request (client*stride + sequence): a repeated
# key would be a result-cache HIT in both configurations, which measures
# the cache, not the control path. Unique keys force a genuine execution
# every time — the ON config's win is exactly the prepared-plan reuse +
# fast path the ISSUE bounds. (Key presence does not change the cost: the
# scan+filter runs either way; a separate known-present probe validates
# results before measurement.)
KNOWN_PRESENT_KEY = 7  # exists at every tpch scale


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _latency_summary(lat_s) -> dict:
    s = sorted(lat_s)
    return {
        "requests": len(s),
        "p50_ms": round(_percentile(s, 0.50) * 1e3, 3),
        "p95_ms": round(_percentile(s, 0.95) * 1e3, 3),
        "p99_ms": round(_percentile(s, 0.99) * 1e3, 3),
        "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else 0.0,
    }


def run_config(coord_url: str, serving_on: bool, clients: int,
               requests_per_client: int, mix=("point", "point", "cached",
                                              "uncached", "point"),
               key_base: int = None) -> dict:
    """One measured configuration: C threads, each its own DBAPI
    connection, round-robin over the workload mix. Returns the stats
    block (qps, latency summaries per class, failure count).
    ``key_base`` offsets the unique point keys — every measured stage of
    a sweep gets a disjoint range so the shared result cache can never
    serve one stage the previous stage's keys."""
    from trino_tpu.client import dbapi
    from trino_tpu.obs import metrics as M

    props = {
        # the warm DATA path (PR 2 result cache + PR 7 device cache) is on
        # in BOTH configurations — this bench isolates the CONTROL path
        # (prepared plans + fast path), composing with the caches the way
        # a serving deployment would run
        "result_cache_enabled": "true",
        "device_cache_enabled": "true",
        "short_query_fast_path": "true" if serving_on else "false",
    }
    # warmup: compile the executor/worker paths for every statement shape
    # so the measurement sees steady-state serving, not jit compiles —
    # and validate the point shape returns the known-present row
    warm = dbapi.connect(coordinator_url=coord_url, **props).cursor()
    if serving_on:
        warm.execute(POINT_SQL, (KNOWN_PRESENT_KEY,))
    else:
        warm.execute(POINT_SQL.replace("?", str(KNOWN_PRESENT_KEY)))
    assert warm.rowcount == 1, "point probe must hit a known row"
    warm.execute(CACHED_SQL)
    warm.execute(UNCACHED_SQL.format(k=0))

    fast0 = M.FAST_PATH_QUERIES.value("fast-path")
    dist0 = M.FAST_PATH_QUERIES.value("distributed")
    latencies = {"point": [], "cached": [], "uncached": []}
    # per-phase wall from each response's queryStats.timeline (the phase
    # ledger): where a p99 regression LIVES — queued vs plan vs device —
    # which is the attribution the QPS_r02 scaling round needs
    phase_latencies = {}
    lat_lock = threading.Lock()
    failures = []

    def client_loop(ci: int):
        cur = dbapi.connect(coordinator_url=coord_url, **props).cursor()
        for r in range(requests_per_client):
            kind = mix[(ci + r) % len(mix)]
            t0 = time.perf_counter()
            try:
                # keys are unique per request AND offset per CONFIG: the
                # result cache is shared server state with a 60s TTL, so
                # reusing the OFF run's keys would serve the ON run's
                # "uncached"/"point" classes as cross-config cache HITs —
                # measuring the cache instead of the control path
                base = key_base if key_base is not None else (
                    2_000_000 if serving_on else 1_000_000)
                if kind == "point":
                    k = base + ci * 100_000 + r  # unique per request
                    if serving_on:
                        cur.execute(POINT_SQL, (k,))
                    else:
                        # both-off baseline: literal substitution, no
                        # PREPARE round-trip, plan cache misses on every
                        # distinct key (the pre-PR serving reality)
                        cur.execute(POINT_SQL.replace("?", str(k)))
                elif kind == "cached":
                    cur.execute(CACHED_SQL)
                else:
                    cur.execute(UNCACHED_SQL.format(
                        k=base + (ci * 131 + r) % 997))
            except Exception as e:  # noqa: BLE001 — counted, not fatal
                failures.append(f"{kind}: {e}")
                continue
            dt = time.perf_counter() - t0
            tl = (getattr(cur, "stats", None) or {}).get("timeline")
            with lat_lock:
                latencies[kind].append(dt)
                if tl:
                    for phase, seconds in tl["phases"].items():
                        phase_latencies.setdefault(phase, []).append(seconds)

    threads = [threading.Thread(target=client_loop, args=(ci,))
               for ci in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    total = sum(len(v) for v in latencies.values())
    return {
        "serving_on": serving_on,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "wall_s": round(wall, 3),
        "qps": round(total / wall, 2) if wall > 0 else 0.0,
        "failures": len(failures),
        "failure_samples": failures[:3],
        "paths": {
            "fast_path": int(M.FAST_PATH_QUERIES.value("fast-path") - fast0),
            "distributed": int(
                M.FAST_PATH_QUERIES.value("distributed") - dist0),
        },
        "latency": {k: _latency_summary(v) for k, v in latencies.items()},
        "phase_latency": {phase: _latency_summary(v)
                          for phase, v in sorted(phase_latencies.items())},
    }


def run_point_only(coord_url: str, serving_on: bool, clients: int,
                   requests_per_client: int, key_base: int = None) -> dict:
    """The acceptance mix: point lookups only (the serving shape the
    ISSUE's >=Nx bound is defined over)."""
    return run_config(coord_url, serving_on, clients, requests_per_client,
                      mix=("point",), key_base=key_base)


def run_sweep(coord_url: str, sweep, total_requests: int = 256,
              key_offset: int = 0) -> list:
    """The concurrency scaling curve (ISSUE 12 / QPS_r02): the serving
    configuration's point mix at each client count, same cluster, each
    stage on a DISJOINT key range. ``total_requests`` is held roughly
    constant across stages so each stage measures a similar window;
    ``key_offset`` keeps REPEATED sweeps on fresh keys (the shared
    result cache must never serve one repetition the previous one's
    rows)."""
    entries = []
    for i, clients in enumerate(sweep):
        per_client = max(4, total_requests // max(1, clients))
        stage = run_point_only(
            coord_url, True, clients, per_client,
            key_base=10_000_000 + key_offset + i * 5_000_000)
        lat = stage["latency"]["point"]
        entry = {
            "clients": clients,
            "requests": lat["requests"],
            "qps": stage["qps"],
            "p50_ms": lat["p50_ms"],
            "p95_ms": lat["p95_ms"],
            "p99_ms": lat["p99_ms"],
            "failures": stage["failures"],
        }
        entries.append(entry)
        print(f"  sweep c={clients:>2}: {entry['qps']:>7} qps  "
              f"p50 {entry['p50_ms']}ms  p99 {entry['p99_ms']}ms",
              flush=True)
    return entries


def _tune_gc_for_measurement() -> None:
    """Measurement hygiene for the in-process harness: freeze the booted
    servers' object graph out of GC scanning and raise the gen-0
    threshold, so collector pauses (10-40ms on the long-lived graph)
    stop landing in the p99 of a 2ms serving path. A real deployment
    applies the same tuning to its server processes."""
    import gc

    gc.collect()
    gc.freeze()
    gc.set_threshold(200_000, 50, 50)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=50,
                    help="requests per client per configuration")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--check", action="store_true",
                    help="quick tier-1 mode: small N, assert the serving "
                    "speedup AND the dispatcher scaling gate (QPS at 8 "
                    "clients strictly above QPS at 2)")
    ap.add_argument("--min-speedup", type=float, default=None,
                    help="required ON/OFF qps ratio on the point mix "
                    "(default: 3.0, or 2.0 under --check for CI headroom)")
    ap.add_argument("--sweep", default="1,2,4,8,16,32",
                    help="comma-separated client counts for the scaling "
                    "sweep (full mode; '' disables)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    min_speedup = args.min_speedup if args.min_speedup is not None else (
        2.0 if args.check else 3.0)
    if args.check:
        args.clients, args.requests = 2, 20

    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    workers = [WorkerServer(coordinator_url=coord.base_url,
                            node_id=f"qps{i}") for i in range(args.workers)]
    for w in workers:
        w.start()
    assert coord.registry.wait_for_workers(args.workers, timeout=30.0)

    try:
        print(f"# point-lookup mix: {args.clients} clients x "
              f"{args.requests} requests per config", flush=True)
        off_point = run_point_only(coord.base_url, False, args.clients,
                                   args.requests)
        print(f"  serving OFF: {off_point['qps']} qps "
              f"(p50 {off_point['latency']['point']['p50_ms']}ms)",
              flush=True)
        on_point = run_point_only(coord.base_url, True, args.clients,
                                  args.requests)
        print(f"  serving ON : {on_point['qps']} qps "
              f"(p50 {on_point['latency']['point']['p50_ms']}ms, "
              f"fast-path {on_point['paths']['fast_path']})", flush=True)
        speedup = (on_point["qps"] / off_point["qps"]
                   if off_point["qps"] > 0 else float("inf"))
        print(f"  speedup: {speedup:.2f}x (required {min_speedup}x)",
              flush=True)

        result = {
            "bench": "qps",
            "round": 2,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
            "workers": args.workers,
            "point_mix": {"off": off_point, "on": on_point,
                          "speedup": round(speedup, 3),
                          "min_speedup": min_speedup},
        }
        problems = []
        if off_point["failures"] + on_point["failures"]:
            problems.append(
                f"failures={off_point['failures'] + on_point['failures']}")
        if speedup < min_speedup:
            problems.append(f"speedup {speedup:.2f}x < {min_speedup}x")

        if args.check:
            # the dispatcher scaling gate (tier-1, CPU-sized): QPS at 8
            # clients must be STRICTLY above QPS at 2 — a serving plane
            # that stops scaling with concurrency is a regression, caught
            # like a kernel regression. On a SINGLE-core box the strict
            # form is physically unattainable (2 closed-loop clients
            # already saturate the core, so added concurrency can only
            # queue), so there the gate asserts saturation HOLD instead:
            # 8 clients must keep >= 75% of the 2-client throughput — a
            # thread-pile-up / lost-keep-alive regression collapses this.
            # Reps interleave and compare best-of to ride out CPU steal.
            _tune_gc_for_measurement()
            single_core = (os.cpu_count() or 1) <= 1
            print("# scaling gate (serving ON, point mix, "
                  + ("single-core hold >= 0.75x" if single_core
                     else "strict 8 > 2") + ")", flush=True)
            q2, q8, fails = [], [], 0
            for rep in range(2):
                scale = run_sweep(coord.base_url, (2, 8),
                                  total_requests=64,
                                  key_offset=rep * 50_000_000)
                q2.append(scale[0]["qps"])
                q8.append(scale[-1]["qps"])
                fails += scale[0]["failures"] + scale[-1]["failures"]
            best2, best8 = max(q2), max(q8)
            gate_ok = (best8 >= 0.75 * best2 if single_core
                       else best8 > best2)
            result["scaling_gate"] = {
                "mode": ("single-core-hold" if single_core else "strict"),
                "c2_qps": best2, "c8_qps": best8, "ok": bool(gate_ok),
            }
            if fails:
                problems.append("scaling-gate request failures")
            if not gate_ok:
                problems.append(
                    f"no scaling: {best8} qps at 8 clients vs "
                    f"{best2} qps at 2 clients "
                    f"({result['scaling_gate']['mode']})")
        else:
            # full mode: the concurrency sweep (the r02 headline) + the
            # mixed workload
            sweep_counts = tuple(
                int(c) for c in args.sweep.split(",") if c.strip())
            if sweep_counts:
                _tune_gc_for_measurement()
                print("# concurrency sweep (serving ON, point mix)",
                      flush=True)
                sweep = run_sweep(coord.base_url, sweep_counts,
                                  total_requests=args.requests * 8)
                by_clients = {e["clients"]: e for e in sweep}
                result["sweep"] = {"clients": list(sweep_counts),
                                   "point": sweep}
                peak = max(e["qps"] for e in sweep)
                result["sweep"]["peak_qps"] = peak
                # the ISSUE 12 acceptance record, measured honestly:
                # rising past 4 clients, the 16-client throughput vs the
                # r01 4-client ceiling (220 qps), and the p99 ratio
                c4, c16 = by_clients.get(4), by_clients.get(16)
                if c4 and c16:
                    single_core = (os.cpu_count() or 1) <= 1
                    accept = {
                        "cpu_count": os.cpu_count(),
                        "r01_4client_ceiling_qps": 220.0,
                        "c4_qps": c4["qps"], "c16_qps": c16["qps"],
                        "rising_past_4_clients": c16["qps"] > c4["qps"],
                        "holding_past_4_clients":
                            c16["qps"] >= 0.75 * c4["qps"],
                        "c16_ge_2x_r01_ceiling": c16["qps"] >= 440.0,
                        "p99_ratio_c16_over_c4": round(
                            c16["p99_ms"] / c4["p99_ms"], 3)
                        if c4["p99_ms"] else None,
                        "p99_within_2x": bool(
                            c4["p99_ms"]
                            and c16["p99_ms"] <= 2.0 * c4["p99_ms"]),
                    }
                    result["accept"] = accept
                    # on a single-core box a saturated closed loop cannot
                    # RISE past the core's ceiling (throughput ~ 1/service
                    # time regardless of clients): require hold there,
                    # strict rise on real multi-core serving hardware
                    if single_core:
                        if not accept["holding_past_4_clients"]:
                            problems.append(
                                "QPS collapsed past 4 clients "
                                f"({c4['qps']} -> {c16['qps']})")
                    elif not accept["rising_past_4_clients"]:
                        problems.append(
                            "QPS not rising past 4 clients "
                            f"({c4['qps']} -> {c16['qps']})")
                if any(e["failures"] for e in sweep):
                    problems.append("sweep request failures")
            print("# mixed workload", flush=True)
            off_mix = run_config(coord.base_url, False, args.clients,
                                 args.requests)
            on_mix = run_config(coord.base_url, True, args.clients,
                                args.requests)
            print(f"  mixed OFF: {off_mix['qps']} qps | "
                  f"ON: {on_mix['qps']} qps", flush=True)
            result["mixed"] = {"off": off_mix, "on": on_mix}

        result["ok"] = not problems
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "QPS_r02.json")
        if args.check and args.out is None:
            out = None  # quick mode never clobbers the recorded round
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=2)
            print(f"wrote {out}", flush=True)
        if problems:
            print("FAIL: " + "; ".join(problems), file=sys.stderr)
            return 1
        print("OK", flush=True)
        return 0
    finally:
        for w in workers:
            w.stop()
        coord.stop()


if __name__ == "__main__":
    sys.exit(main())
