"""QPS microbench: the serving-path trajectory (queries per second).

Every BENCH_r* round measures single-query throughput; heavy traffic is
queries per SECOND. This bench boots a real coordinator + N workers in
one process (the DistributedQueryRunner idiom the test suite uses),
drives C concurrent DBAPI clients over a mixed serving workload, and
measures the two control-plane configurations ISSUE 10 ships —
and, since ISSUE 12's dispatcher/executor split, the CONCURRENCY
SCALING SWEEP: the serving configuration's point mix at client counts
{1, 2, 4, 8, 16, 32} (per-stage disjoint key ranges so the shared
result cache can never flatter a later stage). Since ISSUE 17 the full
run adds the ADVERSARIAL-TENANT fairness phase: a heavy tenant floods
long scans while a light tenant runs point lookups on a cluster booted
with the heavy/light resource-group config (``run_fairness``); the
light tenant's contended p99 must stay within 1.5x of its solo p99 —
emitted together as ``QPS_r03.json`` and folded into TRAJECTORY.json.
``--check`` additionally runs the dispatcher scaling gate (see main).

- **serving ON** — prepared point lookups through PREPARE/EXECUTE (the
  parameterized plan caches once; every EXECUTE is bind + run) with the
  short-query fast path enabled (single-stage plans run
  coordinator-local, zero task HTTP round-trips);
- **serving OFF** — the same statements as plain SQL with literals
  substituted client-side, fast path disabled: every request pays
  parse/analyze/plan/optimize + fragment/schedule/exchange.

Workload mix (per client, round-robin):
- ``point``   — prepared point lookup on ``orders`` (the serving shape);
- ``cached``  — a repeated aggregate with the result cache on (HIT path);
- ``uncached``— an aggregate over a shifting predicate (MISS every time).

Emits ``QPS_r02.json`` next to the other bench artifacts: per-config
qps + p50/p95/p99 latency per workload class, the per-path breakdown
(fast-path vs distributed counts from the coordinator's own metrics),
the ON/OFF speedup on the point mix, and the concurrency sweep with
the ISSUE 12 acceptance record.

Run:    python microbench/qps.py [--clients C] [--requests N] [--workers W]
                                 [--sweep 1,2,4,8,16,32]
Check:  python microbench/qps.py --check [--min-speedup X]
        (tier-1 quick mode, small N, CPU-runnable: asserts the serving
        config clears ``min_speedup`` x on the point-lookup mix AND the
        dispatcher scaling gate — QPS at 8 clients strictly above 2
        clients on multi-core boxes; saturation hold on single-core)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POINT_SQL = ("select o_orderkey, o_totalprice, o_orderstatus "
             "from orders where o_orderkey = ?")
CACHED_SQL = ("select o_orderstatus, count(*), sum(o_totalprice) "
              "from orders group by o_orderstatus order by o_orderstatus")
UNCACHED_SQL = ("select count(*), max(o_totalprice) from orders "
                "where o_orderkey > {k}")

# Point keys are UNIQUE per request (client*stride + sequence): a repeated
# key would be a result-cache HIT in both configurations, which measures
# the cache, not the control path. Unique keys force a genuine execution
# every time — the ON config's win is exactly the prepared-plan reuse +
# fast path the ISSUE bounds. (Key presence does not change the cost: the
# scan+filter runs either way; a separate known-present probe validates
# results before measurement.)
KNOWN_PRESENT_KEY = 7  # exists at every tpch scale


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _latency_summary(lat_s) -> dict:
    s = sorted(lat_s)
    return {
        "requests": len(s),
        "p50_ms": round(_percentile(s, 0.50) * 1e3, 3),
        "p95_ms": round(_percentile(s, 0.95) * 1e3, 3),
        "p99_ms": round(_percentile(s, 0.99) * 1e3, 3),
        "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else 0.0,
    }


def run_config(coord_url: str, serving_on: bool, clients: int,
               requests_per_client: int, mix=("point", "point", "cached",
                                              "uncached", "point"),
               key_base: int = None, user: str = None) -> dict:
    """One measured configuration: C threads, each its own DBAPI
    connection, round-robin over the workload mix. Returns the stats
    block (qps, latency summaries per class, failure count).
    ``key_base`` offsets the unique point keys — every measured stage of
    a sweep gets a disjoint range so the shared result cache can never
    serve one stage the previous stage's keys. ``user`` rides the
    X-Trino-User header (the resource-group selector input the fairness
    phase routes tenants by)."""
    from trino_tpu.client import dbapi
    from trino_tpu.obs import metrics as M

    props = {
        # the warm DATA path (PR 2 result cache + PR 7 device cache) is on
        # in BOTH configurations — this bench isolates the CONTROL path
        # (prepared plans + fast path), composing with the caches the way
        # a serving deployment would run
        "result_cache_enabled": "true",
        "device_cache_enabled": "true",
        "short_query_fast_path": "true" if serving_on else "false",
    }
    # warmup: compile the executor/worker paths for every statement shape
    # so the measurement sees steady-state serving, not jit compiles —
    # and validate the point shape returns the known-present row
    warm = dbapi.connect(coordinator_url=coord_url, user=user,
                         **props).cursor()
    if serving_on:
        warm.execute(POINT_SQL, (KNOWN_PRESENT_KEY,))
    else:
        warm.execute(POINT_SQL.replace("?", str(KNOWN_PRESENT_KEY)))
    assert warm.rowcount == 1, "point probe must hit a known row"
    warm.execute(CACHED_SQL)
    warm.execute(UNCACHED_SQL.format(k=0))

    fast0 = M.FAST_PATH_QUERIES.value("fast-path")
    dist0 = M.FAST_PATH_QUERIES.value("distributed")
    latencies = {"point": [], "cached": [], "uncached": []}
    # per-phase wall from each response's queryStats.timeline (the phase
    # ledger): where a p99 regression LIVES — queued vs plan vs device —
    # which is the attribution the QPS_r02 scaling round needs
    phase_latencies = {}
    lat_lock = threading.Lock()
    failures = []

    def client_loop(ci: int):
        cur = dbapi.connect(coordinator_url=coord_url, user=user,
                            **props).cursor()
        for r in range(requests_per_client):
            kind = mix[(ci + r) % len(mix)]
            t0 = time.perf_counter()
            try:
                # keys are unique per request AND offset per CONFIG: the
                # result cache is shared server state with a 60s TTL, so
                # reusing the OFF run's keys would serve the ON run's
                # "uncached"/"point" classes as cross-config cache HITs —
                # measuring the cache instead of the control path
                base = key_base if key_base is not None else (
                    2_000_000 if serving_on else 1_000_000)
                if kind == "point":
                    k = base + ci * 100_000 + r  # unique per request
                    if serving_on:
                        cur.execute(POINT_SQL, (k,))
                    else:
                        # both-off baseline: literal substitution, no
                        # PREPARE round-trip, plan cache misses on every
                        # distinct key (the pre-PR serving reality)
                        cur.execute(POINT_SQL.replace("?", str(k)))
                elif kind == "cached":
                    cur.execute(CACHED_SQL)
                else:
                    cur.execute(UNCACHED_SQL.format(
                        k=base + (ci * 131 + r) % 997))
            except Exception as e:  # noqa: BLE001 — counted, not fatal
                failures.append(f"{kind}: {e}")
                continue
            dt = time.perf_counter() - t0
            tl = (getattr(cur, "stats", None) or {}).get("timeline")
            with lat_lock:
                latencies[kind].append(dt)
                if tl:
                    for phase, seconds in tl["phases"].items():
                        phase_latencies.setdefault(phase, []).append(seconds)

    threads = [threading.Thread(target=client_loop, args=(ci,))
               for ci in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    total = sum(len(v) for v in latencies.values())
    return {
        "serving_on": serving_on,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "wall_s": round(wall, 3),
        "qps": round(total / wall, 2) if wall > 0 else 0.0,
        "failures": len(failures),
        "failure_samples": failures[:3],
        "paths": {
            "fast_path": int(M.FAST_PATH_QUERIES.value("fast-path") - fast0),
            "distributed": int(
                M.FAST_PATH_QUERIES.value("distributed") - dist0),
        },
        "latency": {k: _latency_summary(v) for k, v in latencies.items()},
        "phase_latency": {phase: _latency_summary(v)
                          for phase, v in sorted(phase_latencies.items())},
    }


def run_point_only(coord_url: str, serving_on: bool, clients: int,
                   requests_per_client: int, key_base: int = None,
                   user: str = None) -> dict:
    """The acceptance mix: point lookups only (the serving shape the
    ISSUE's >=Nx bound is defined over)."""
    return run_config(coord_url, serving_on, clients, requests_per_client,
                      mix=("point",), key_base=key_base, user=user)


# ----------------------------------------------------- adversarial tenants
# The ISSUE 17 fairness phase: a HEAVY tenant floods long scans while a
# LIGHT tenant runs point lookups. With the resource-group config below,
# the heavy tenant's group caps at ONE concurrent query and drains at 1/4
# the light group's weight — so the light tenant's p99 under the flood
# must stay within ``FAIRNESS_MAX_RATIO`` of its SOLO p99 (measured on
# the same cluster, flood off). Without groups the shared FIFO queue
# interleaves the tenants and the light p99 inherits the heavy scans'
# service times. On a SINGLE-core box the absolute bound is physically
# unattainable (one running scan owns the only core for its whole
# service time, which already exceeds 0.5x the light p99 — no admission
# scheme can preempt it), so there the gate asserts the isolation GAIN
# instead: the groups configuration must cut the contended/solo p99
# ratio by >= FAIRNESS_MIN_GAIN vs the no-groups baseline — the same
# single-core fallback shape as the dispatcher scaling gate above.
FAIRNESS_MAX_RATIO = 1.5
FAIRNESS_MIN_GAIN = 2.0
FAIRNESS_GROUPS_CONFIG = {
    "root_groups": [{
        "name": "global",
        "hard_concurrency_limit": 16,
        "max_queued": 500,
        "sub_groups": [
            {"name": "heavy", "hard_concurrency_limit": 1, "weight": 1,
             "max_queued": 400},
            {"name": "light", "hard_concurrency_limit": 8, "weight": 4,
             "max_queued": 200},
        ],
    }],
    "selectors": [
        {"user": "heavy", "group": "global.heavy"},
        {"user": "light", "group": "global.light"},
        {"group": "global"},
    ],
}
# ONE fixed statement for the flood: the result cache is off for the
# heavy tenant, so every request still pays the full scan+aggregate
# (device cache off: re-staged every time) — but the plan shape compiles
# exactly once. A shifting literal would make every request a fresh jit
# COMPILE, turning the flood into a compile storm that saturates the CPU
# outside the admission path — measuring the compiler, not the groups.
HEAVY_SQL = ("select o_custkey, count(*), sum(o_totalprice) from orders "
             "where o_orderkey > 0 group by o_custkey")
_HEAVY_PROPS = dict(result_cache_enabled="false",
                    device_cache_enabled="false",
                    short_query_fast_path="false")


def _heavy_flood(coord_url: str, stop: threading.Event,
                 threads: int = 4) -> tuple:
    """Start the heavy tenant's closed-loop scan flood; returns
    (threads, completed counter). Caches OFF so every request pays a
    real scan."""
    from trino_tpu.client import dbapi

    completed = [0]
    count_lock = threading.Lock()

    def loop(ci: int):
        cur = dbapi.connect(coordinator_url=coord_url, user="heavy",
                            **_HEAVY_PROPS).cursor()
        while not stop.is_set():
            try:
                cur.execute(HEAVY_SQL)
                with count_lock:
                    completed[0] += 1
            except Exception:  # noqa: BLE001 — flood pressure, not a gate
                pass

    ts = [threading.Thread(target=loop, args=(ci,), daemon=True)
          for ci in range(threads)]
    for t in ts:
        t.start()
    return ts, completed


def _fairness_phase(groups_config, workers: int, light_clients: int,
                    light_requests: int, heavy_threads: int,
                    key_base: int, label: str) -> dict:
    """One measured cluster: boot with ``groups_config`` (None = the
    default single-group tree, the no-groups baseline), warm the heavy
    shape, measure the light tenant solo, then under the heavy flood.
    Returns solo/contended latency blocks + the contended/solo p99
    ratio."""
    import gc

    from trino_tpu.client import dbapi
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    # drain the PREVIOUS cluster's garbage before measuring on this one:
    # on a single core a gen-2 collection of the dead server graph lands
    # squarely in the solo p99 otherwise
    gc.collect()
    coord = CoordinatorServer(resource_groups_config=groups_config)
    coord.start()
    wks = [WorkerServer(coordinator_url=coord.base_url,
                        node_id=f"fair-{label}{i}") for i in range(workers)]
    for w in wks:
        w.start()
    assert coord.registry.wait_for_workers(workers, timeout=30.0)
    try:
        # warm the heavy shape ONCE: the flood must measure steady-state
        # scan pressure, not the first query's jit compile
        dbapi.connect(coordinator_url=coord.base_url, user="heavy",
                      **_HEAVY_PROPS).cursor().execute(HEAVY_SQL)
        solo = run_point_only(coord.base_url, True, light_clients,
                              light_requests, key_base=key_base,
                              user="light")
        solo_lat = solo["latency"]["point"]
        stop = threading.Event()
        flood, completed = _heavy_flood(coord.base_url, stop,
                                        threads=heavy_threads)
        try:
            time.sleep(0.3)  # let the flood saturate its group first
            contended = run_point_only(
                coord.base_url, True, light_clients, light_requests,
                key_base=key_base + 5_000_000, user="light")
        finally:
            stop.set()
            for t in flood:
                t.join(timeout=30.0)
        cont_lat = contended["latency"]["point"]
        ratio = (cont_lat["p99_ms"] / solo_lat["p99_ms"]
                 if solo_lat["p99_ms"] else None)
        print(f"  {label:>9} solo p99 {solo_lat['p99_ms']}ms | contended "
              f"p99 {cont_lat['p99_ms']}ms ({contended['qps']} qps, heavy "
              f"completed {completed[0]}) -> ratio "
              f"{ratio if ratio is None else round(ratio, 2)}x", flush=True)
        return {
            "heavy_completed": completed[0],
            "solo": {"qps": solo["qps"], "p50_ms": solo_lat["p50_ms"],
                     "p99_ms": solo_lat["p99_ms"],
                     "failures": solo["failures"]},
            "contended": {"qps": contended["qps"],
                          "p50_ms": cont_lat["p50_ms"],
                          "p99_ms": cont_lat["p99_ms"],
                          "failures": contended["failures"]},
            "p99_ratio": round(ratio, 3) if ratio is not None else None,
            "failures": solo["failures"] + contended["failures"],
        }
    finally:
        for w in wks:
            w.stop()
        coord.stop()


def run_fairness(workers: int, light_clients: int = 2,
                 light_requests: int = 30, heavy_threads: int = 4) -> dict:
    """The adversarial-tenant measurement: the light tenant's
    contended/solo p99 ratio with the heavy/light group config enforcing
    isolation, against the same ratio on a no-groups baseline cluster.
    ok on multi-core: the groups ratio holds ``FAIRNESS_MAX_RATIO``; on
    a single core (where an absolute bound is unattainable — see
    FAIRNESS_GROUPS_CONFIG): the groups cut the baseline ratio by
    >= ``FAIRNESS_MIN_GAIN``."""
    grouped = _fairness_phase(FAIRNESS_GROUPS_CONFIG, workers,
                              light_clients, light_requests, heavy_threads,
                              key_base=70_000_000, label="groups")
    baseline = _fairness_phase(None, workers, light_clients,
                               light_requests, heavy_threads,
                               key_base=90_000_000, label="no-groups")
    ratio, base_ratio = grouped["p99_ratio"], baseline["p99_ratio"]
    gain = (round(base_ratio / ratio, 3)
            if ratio and base_ratio else None)
    single_core = (os.cpu_count() or 1) <= 1
    if single_core:
        ok = bool(gain is not None and gain >= FAIRNESS_MIN_GAIN)
    else:
        ok = bool(ratio is not None and ratio <= FAIRNESS_MAX_RATIO)
    ok = ok and not grouped["failures"] and not baseline["failures"]
    mode = "single-core-gain" if single_core else "strict"
    print(f"  isolation gain {gain}x (mode {mode}: "
          + (f"gain >= {FAIRNESS_MIN_GAIN}" if single_core
             else f"ratio <= {FAIRNESS_MAX_RATIO}")
          + f") -> {'ok' if ok else 'FAIL'}", flush=True)
    return {
        "groups_config": "heavy(limit=1,w=1) vs light(limit=8,w=4)",
        "heavy_threads": heavy_threads,
        "light_clients": light_clients,
        "mode": mode,
        "cpu_count": os.cpu_count(),
        "heavy_completed": grouped["heavy_completed"],
        "solo": grouped["solo"],
        "contended": grouped["contended"],
        "p99_ratio": ratio,
        "max_ratio": FAIRNESS_MAX_RATIO,
        "baseline": baseline,
        "isolation_gain": gain,
        "min_gain": FAIRNESS_MIN_GAIN,
        "ok": ok,
    }


def run_sweep(coord_url: str, sweep, total_requests: int = 256,
              key_offset: int = 0) -> list:
    """The concurrency scaling curve (ISSUE 12 / QPS_r02): the serving
    configuration's point mix at each client count, same cluster, each
    stage on a DISJOINT key range. ``total_requests`` is held roughly
    constant across stages so each stage measures a similar window;
    ``key_offset`` keeps REPEATED sweeps on fresh keys (the shared
    result cache must never serve one repetition the previous one's
    rows)."""
    entries = []
    for i, clients in enumerate(sweep):
        per_client = max(4, total_requests // max(1, clients))
        stage = run_point_only(
            coord_url, True, clients, per_client,
            key_base=10_000_000 + key_offset + i * 5_000_000)
        lat = stage["latency"]["point"]
        entry = {
            "clients": clients,
            "requests": lat["requests"],
            "qps": stage["qps"],
            "p50_ms": lat["p50_ms"],
            "p95_ms": lat["p95_ms"],
            "p99_ms": lat["p99_ms"],
            "failures": stage["failures"],
        }
        entries.append(entry)
        print(f"  sweep c={clients:>2}: {entry['qps']:>7} qps  "
              f"p50 {entry['p50_ms']}ms  p99 {entry['p99_ms']}ms",
              flush=True)
    return entries


def _tune_gc_for_measurement() -> None:
    """Measurement hygiene for the in-process harness: freeze the booted
    servers' object graph out of GC scanning and raise the gen-0
    threshold, so collector pauses (10-40ms on the long-lived graph)
    stop landing in the p99 of a 2ms serving path. A real deployment
    applies the same tuning to its server processes."""
    import gc

    gc.collect()
    gc.freeze()
    gc.set_threshold(200_000, 50, 50)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=50,
                    help="requests per client per configuration")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--check", action="store_true",
                    help="quick tier-1 mode: small N, assert the serving "
                    "speedup AND the dispatcher scaling gate (QPS at 8 "
                    "clients strictly above QPS at 2)")
    ap.add_argument("--min-speedup", type=float, default=None,
                    help="required ON/OFF qps ratio on the point mix "
                    "(default: 3.0, or 2.0 under --check for CI headroom)")
    ap.add_argument("--sweep", default="1,2,4,8,16,32",
                    help="comma-separated client counts for the scaling "
                    "sweep (full mode; '' disables)")
    ap.add_argument("--no-fairness", action="store_true",
                    help="skip the adversarial-tenant fairness phase "
                    "(full mode runs it by default)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    min_speedup = args.min_speedup if args.min_speedup is not None else (
        2.0 if args.check else 3.0)
    if args.check:
        args.clients, args.requests = 2, 20

    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    workers = [WorkerServer(coordinator_url=coord.base_url,
                            node_id=f"qps{i}") for i in range(args.workers)]
    for w in workers:
        w.start()
    assert coord.registry.wait_for_workers(args.workers, timeout=30.0)

    try:
        print(f"# point-lookup mix: {args.clients} clients x "
              f"{args.requests} requests per config", flush=True)
        off_point = run_point_only(coord.base_url, False, args.clients,
                                   args.requests)
        print(f"  serving OFF: {off_point['qps']} qps "
              f"(p50 {off_point['latency']['point']['p50_ms']}ms)",
              flush=True)
        on_point = run_point_only(coord.base_url, True, args.clients,
                                  args.requests)
        print(f"  serving ON : {on_point['qps']} qps "
              f"(p50 {on_point['latency']['point']['p50_ms']}ms, "
              f"fast-path {on_point['paths']['fast_path']})", flush=True)
        speedup = (on_point["qps"] / off_point["qps"]
                   if off_point["qps"] > 0 else float("inf"))
        print(f"  speedup: {speedup:.2f}x (required {min_speedup}x)",
              flush=True)

        result = {
            "bench": "qps",
            "round": 3,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
            "workers": args.workers,
            "point_mix": {"off": off_point, "on": on_point,
                          "speedup": round(speedup, 3),
                          "min_speedup": min_speedup},
        }
        problems = []
        if off_point["failures"] + on_point["failures"]:
            problems.append(
                f"failures={off_point['failures'] + on_point['failures']}")
        if speedup < min_speedup:
            problems.append(f"speedup {speedup:.2f}x < {min_speedup}x")

        if args.check:
            # the dispatcher scaling gate (tier-1, CPU-sized): QPS at 8
            # clients must be STRICTLY above QPS at 2 — a serving plane
            # that stops scaling with concurrency is a regression, caught
            # like a kernel regression. On a SINGLE-core box the strict
            # form is physically unattainable (2 closed-loop clients
            # already saturate the core, so added concurrency can only
            # queue), so there the gate asserts saturation HOLD instead:
            # 8 clients must keep >= 75% of the 2-client throughput — a
            # thread-pile-up / lost-keep-alive regression collapses this.
            # Reps interleave and compare best-of to ride out CPU steal.
            _tune_gc_for_measurement()
            single_core = (os.cpu_count() or 1) <= 1
            print("# scaling gate (serving ON, point mix, "
                  + ("single-core hold >= 0.75x" if single_core
                     else "strict 8 > 2") + ")", flush=True)
            q2, q8, fails = [], [], 0
            for rep in range(2):
                scale = run_sweep(coord.base_url, (2, 8),
                                  total_requests=64,
                                  key_offset=rep * 50_000_000)
                q2.append(scale[0]["qps"])
                q8.append(scale[-1]["qps"])
                fails += scale[0]["failures"] + scale[-1]["failures"]
            best2, best8 = max(q2), max(q8)
            gate_ok = (best8 >= 0.75 * best2 if single_core
                       else best8 > best2)
            result["scaling_gate"] = {
                "mode": ("single-core-hold" if single_core else "strict"),
                "c2_qps": best2, "c8_qps": best8, "ok": bool(gate_ok),
            }
            if fails:
                problems.append("scaling-gate request failures")
            if not gate_ok:
                problems.append(
                    f"no scaling: {best8} qps at 8 clients vs "
                    f"{best2} qps at 2 clients "
                    f"({result['scaling_gate']['mode']})")
        else:
            # full mode: the concurrency sweep (the r02 headline) + the
            # mixed workload
            sweep_counts = tuple(
                int(c) for c in args.sweep.split(",") if c.strip())
            if sweep_counts:
                _tune_gc_for_measurement()
                print("# concurrency sweep (serving ON, point mix)",
                      flush=True)
                sweep = run_sweep(coord.base_url, sweep_counts,
                                  total_requests=args.requests * 8)
                by_clients = {e["clients"]: e for e in sweep}
                result["sweep"] = {"clients": list(sweep_counts),
                                   "point": sweep}
                peak = max(e["qps"] for e in sweep)
                result["sweep"]["peak_qps"] = peak
                # the ISSUE 12 acceptance record, measured honestly:
                # rising past 4 clients, the 16-client throughput vs the
                # r01 4-client ceiling (220 qps), and the p99 ratio
                c4, c16 = by_clients.get(4), by_clients.get(16)
                if c4 and c16:
                    single_core = (os.cpu_count() or 1) <= 1
                    accept = {
                        "cpu_count": os.cpu_count(),
                        "r01_4client_ceiling_qps": 220.0,
                        "c4_qps": c4["qps"], "c16_qps": c16["qps"],
                        "rising_past_4_clients": c16["qps"] > c4["qps"],
                        "holding_past_4_clients":
                            c16["qps"] >= 0.75 * c4["qps"],
                        "c16_ge_2x_r01_ceiling": c16["qps"] >= 440.0,
                        "p99_ratio_c16_over_c4": round(
                            c16["p99_ms"] / c4["p99_ms"], 3)
                        if c4["p99_ms"] else None,
                        "p99_within_2x": bool(
                            c4["p99_ms"]
                            and c16["p99_ms"] <= 2.0 * c4["p99_ms"]),
                    }
                    result["accept"] = accept
                    # on a single-core box a saturated closed loop cannot
                    # RISE past the core's ceiling (throughput ~ 1/service
                    # time regardless of clients): require hold there,
                    # strict rise on real multi-core serving hardware
                    if single_core:
                        if not accept["holding_past_4_clients"]:
                            problems.append(
                                "QPS collapsed past 4 clients "
                                f"({c4['qps']} -> {c16['qps']})")
                    elif not accept["rising_past_4_clients"]:
                        problems.append(
                            "QPS not rising past 4 clients "
                            f"({c4['qps']} -> {c16['qps']})")
                if any(e["failures"] for e in sweep):
                    problems.append("sweep request failures")
            print("# mixed workload", flush=True)
            off_mix = run_config(coord.base_url, False, args.clients,
                                 args.requests)
            on_mix = run_config(coord.base_url, True, args.clients,
                                args.requests)
            print(f"  mixed OFF: {off_mix['qps']} qps | "
                  f"ON: {on_mix['qps']} qps", flush=True)
            result["mixed"] = {"off": off_mix, "on": on_mix}
            if not args.no_fairness:
                # the ISSUE 17 adversarial-tenant phase: its own cluster,
                # booted with the heavy/light resource-group config
                print("# adversarial tenants (resource groups ON)",
                      flush=True)
                fairness = run_fairness(args.workers)
                result["fairness"] = fairness
                if not fairness["ok"]:
                    problems.append(
                        "fairness: light p99 ratio "
                        f"{fairness['p99_ratio']}x exceeds "
                        f"{fairness['max_ratio']}x (or request failures)")

        result["ok"] = not problems
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "QPS_r03.json")
        if args.check and args.out is None:
            out = None  # quick mode never clobbers the recorded round
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=2)
            print(f"wrote {out}", flush=True)
        if problems:
            print("FAIL: " + "; ".join(problems), file=sys.stderr)
            return 1
        print("OK", flush=True)
        return 0
    finally:
        for w in workers:
            w.stop()
        coord.stop()


if __name__ == "__main__":
    sys.exit(main())
