"""QPS microbench: the serving-path trajectory (queries per second).

Every BENCH_r* round measures single-query throughput; heavy traffic is
queries per SECOND. This bench boots a real coordinator + N workers in
one process (the DistributedQueryRunner idiom the test suite uses),
drives C concurrent DBAPI clients over a mixed serving workload, and
measures the two control-plane configurations ISSUE 10 ships:

- **serving ON** — prepared point lookups through PREPARE/EXECUTE (the
  parameterized plan caches once; every EXECUTE is bind + run) with the
  short-query fast path enabled (single-stage plans run
  coordinator-local, zero task HTTP round-trips);
- **serving OFF** — the same statements as plain SQL with literals
  substituted client-side, fast path disabled: every request pays
  parse/analyze/plan/optimize + fragment/schedule/exchange.

Workload mix (per client, round-robin):
- ``point``   — prepared point lookup on ``orders`` (the serving shape);
- ``cached``  — a repeated aggregate with the result cache on (HIT path);
- ``uncached``— an aggregate over a shifting predicate (MISS every time).

Emits ``QPS_r01.json`` next to the other bench artifacts: per-config
qps + p50/p95/p99 latency per workload class, the per-path breakdown
(fast-path vs distributed counts from the coordinator's own metrics),
and the ON/OFF speedup on the point mix.

Run:    python microbench/qps.py [--clients C] [--requests N] [--workers W]
Check:  python microbench/qps.py --check [--min-speedup X]
        (tier-1 quick mode, small N, CPU-runnable: asserts the serving
        config clears ``min_speedup`` x on the point-lookup mix)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POINT_SQL = ("select o_orderkey, o_totalprice, o_orderstatus "
             "from orders where o_orderkey = ?")
CACHED_SQL = ("select o_orderstatus, count(*), sum(o_totalprice) "
              "from orders group by o_orderstatus order by o_orderstatus")
UNCACHED_SQL = ("select count(*), max(o_totalprice) from orders "
                "where o_orderkey > {k}")

# Point keys are UNIQUE per request (client*stride + sequence): a repeated
# key would be a result-cache HIT in both configurations, which measures
# the cache, not the control path. Unique keys force a genuine execution
# every time — the ON config's win is exactly the prepared-plan reuse +
# fast path the ISSUE bounds. (Key presence does not change the cost: the
# scan+filter runs either way; a separate known-present probe validates
# results before measurement.)
KNOWN_PRESENT_KEY = 7  # exists at every tpch scale


def _percentile(sorted_vals, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[i]


def _latency_summary(lat_s) -> dict:
    s = sorted(lat_s)
    return {
        "requests": len(s),
        "p50_ms": round(_percentile(s, 0.50) * 1e3, 3),
        "p95_ms": round(_percentile(s, 0.95) * 1e3, 3),
        "p99_ms": round(_percentile(s, 0.99) * 1e3, 3),
        "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else 0.0,
    }


def run_config(coord_url: str, serving_on: bool, clients: int,
               requests_per_client: int, mix=("point", "point", "cached",
                                              "uncached", "point")) -> dict:
    """One measured configuration: C threads, each its own DBAPI
    connection, round-robin over the workload mix. Returns the stats
    block (qps, latency summaries per class, failure count)."""
    from trino_tpu.client import dbapi
    from trino_tpu.obs import metrics as M

    props = {
        # the warm DATA path (PR 2 result cache + PR 7 device cache) is on
        # in BOTH configurations — this bench isolates the CONTROL path
        # (prepared plans + fast path), composing with the caches the way
        # a serving deployment would run
        "result_cache_enabled": "true",
        "device_cache_enabled": "true",
        "short_query_fast_path": "true" if serving_on else "false",
    }
    # warmup: compile the executor/worker paths for every statement shape
    # so the measurement sees steady-state serving, not jit compiles —
    # and validate the point shape returns the known-present row
    warm = dbapi.connect(coordinator_url=coord_url, **props).cursor()
    if serving_on:
        warm.execute(POINT_SQL, (KNOWN_PRESENT_KEY,))
    else:
        warm.execute(POINT_SQL.replace("?", str(KNOWN_PRESENT_KEY)))
    assert warm.rowcount == 1, "point probe must hit a known row"
    warm.execute(CACHED_SQL)
    warm.execute(UNCACHED_SQL.format(k=0))

    fast0 = M.FAST_PATH_QUERIES.value("fast-path")
    dist0 = M.FAST_PATH_QUERIES.value("distributed")
    latencies = {"point": [], "cached": [], "uncached": []}
    # per-phase wall from each response's queryStats.timeline (the phase
    # ledger): where a p99 regression LIVES — queued vs plan vs device —
    # which is the attribution the QPS_r02 scaling round needs
    phase_latencies = {}
    lat_lock = threading.Lock()
    failures = []

    def client_loop(ci: int):
        cur = dbapi.connect(coordinator_url=coord_url, **props).cursor()
        for r in range(requests_per_client):
            kind = mix[(ci + r) % len(mix)]
            t0 = time.perf_counter()
            try:
                # keys are unique per request AND offset per CONFIG: the
                # result cache is shared server state with a 60s TTL, so
                # reusing the OFF run's keys would serve the ON run's
                # "uncached"/"point" classes as cross-config cache HITs —
                # measuring the cache instead of the control path
                base = 2_000_000 if serving_on else 1_000_000
                if kind == "point":
                    k = base + ci * 100_000 + r  # unique per request
                    if serving_on:
                        cur.execute(POINT_SQL, (k,))
                    else:
                        # both-off baseline: literal substitution, no
                        # PREPARE round-trip, plan cache misses on every
                        # distinct key (the pre-PR serving reality)
                        cur.execute(POINT_SQL.replace("?", str(k)))
                elif kind == "cached":
                    cur.execute(CACHED_SQL)
                else:
                    cur.execute(UNCACHED_SQL.format(
                        k=base + (ci * 131 + r) % 997))
            except Exception as e:  # noqa: BLE001 — counted, not fatal
                failures.append(f"{kind}: {e}")
                continue
            dt = time.perf_counter() - t0
            tl = (getattr(cur, "stats", None) or {}).get("timeline")
            with lat_lock:
                latencies[kind].append(dt)
                if tl:
                    for phase, seconds in tl["phases"].items():
                        phase_latencies.setdefault(phase, []).append(seconds)

    threads = [threading.Thread(target=client_loop, args=(ci,))
               for ci in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    total = sum(len(v) for v in latencies.values())
    return {
        "serving_on": serving_on,
        "clients": clients,
        "requests_per_client": requests_per_client,
        "wall_s": round(wall, 3),
        "qps": round(total / wall, 2) if wall > 0 else 0.0,
        "failures": len(failures),
        "failure_samples": failures[:3],
        "paths": {
            "fast_path": int(M.FAST_PATH_QUERIES.value("fast-path") - fast0),
            "distributed": int(
                M.FAST_PATH_QUERIES.value("distributed") - dist0),
        },
        "latency": {k: _latency_summary(v) for k, v in latencies.items()},
        "phase_latency": {phase: _latency_summary(v)
                          for phase, v in sorted(phase_latencies.items())},
    }


def run_point_only(coord_url: str, serving_on: bool, clients: int,
                   requests_per_client: int) -> dict:
    """The acceptance mix: point lookups only (the serving shape the
    ISSUE's >=Nx bound is defined over)."""
    return run_config(coord_url, serving_on, clients, requests_per_client,
                      mix=("point",))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests", type=int, default=50,
                    help="requests per client per configuration")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--check", action="store_true",
                    help="quick tier-1 mode: small N, assert speedup")
    ap.add_argument("--min-speedup", type=float, default=None,
                    help="required ON/OFF qps ratio on the point mix "
                    "(default: 3.0, or 2.0 under --check for CI headroom)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    min_speedup = args.min_speedup if args.min_speedup is not None else (
        2.0 if args.check else 3.0)
    if args.check:
        args.clients, args.requests = 2, 20

    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    workers = [WorkerServer(coordinator_url=coord.base_url,
                            node_id=f"qps{i}") for i in range(args.workers)]
    for w in workers:
        w.start()
    assert coord.registry.wait_for_workers(args.workers, timeout=30.0)

    try:
        print(f"# point-lookup mix: {args.clients} clients x "
              f"{args.requests} requests per config", flush=True)
        off_point = run_point_only(coord.base_url, False, args.clients,
                                   args.requests)
        print(f"  serving OFF: {off_point['qps']} qps "
              f"(p50 {off_point['latency']['point']['p50_ms']}ms)",
              flush=True)
        on_point = run_point_only(coord.base_url, True, args.clients,
                                  args.requests)
        print(f"  serving ON : {on_point['qps']} qps "
              f"(p50 {on_point['latency']['point']['p50_ms']}ms, "
              f"fast-path {on_point['paths']['fast_path']})", flush=True)
        speedup = (on_point["qps"] / off_point["qps"]
                   if off_point["qps"] > 0 else float("inf"))
        print(f"  speedup: {speedup:.2f}x (required {min_speedup}x)",
              flush=True)

        result = {
            "bench": "qps",
            "round": 1,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
            "workers": args.workers,
            "point_mix": {"off": off_point, "on": on_point,
                          "speedup": round(speedup, 3),
                          "min_speedup": min_speedup},
        }
        if not args.check:
            # full mode adds the mixed workload (cached/uncached classes)
            print("# mixed workload", flush=True)
            off_mix = run_config(coord.base_url, False, args.clients,
                                 args.requests)
            on_mix = run_config(coord.base_url, True, args.clients,
                                args.requests)
            print(f"  mixed OFF: {off_mix['qps']} qps | "
                  f"ON: {on_mix['qps']} qps", flush=True)
            result["mixed"] = {"off": off_mix, "on": on_mix}

        failures = off_point["failures"] + on_point["failures"]
        ok = speedup >= min_speedup and failures == 0
        result["ok"] = bool(ok)
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "QPS_r01.json")
        if args.check and args.out is None:
            out = None  # quick mode never clobbers the recorded round
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=2)
            print(f"wrote {out}", flush=True)
        if not ok:
            print(f"FAIL: speedup {speedup:.2f}x < {min_speedup}x "
                  f"or failures={failures}", file=sys.stderr)
            return 1
        print("OK", flush=True)
        return 0
    finally:
        for w in workers:
            w.stop()
        coord.stop()


if __name__ == "__main__":
    sys.exit(main())
