"""Data-plane flow-ledger microbench: the per-link transfer trajectory.

BENCH/QPS rounds track throughput and MEMLEDGER tracks bytes-at-rest;
this bench tracks bytes IN MOTION — which link moved how many bytes at
what rate — by reading the flow ledger's own surfaces, so the bench
measures the instrumentation the PR ships:

- **per-link MB/s** — ``FLOW_LEDGER`` rollups over a 2-worker
  distributed TPC-H q3 (``exchange-pull``, ``staging-transfer``,
  ``client-drain``, ``control``) plus a spooled result export
  (``spool-write`` / ``segment-fetch``); absolutes fold into
  TRAJECTORY.json as ``direction: "info"`` (single loopback box);
- **conservation_fraction** — exchange-pull ledger bytes over the serde
  decode-side wire bytes (``trino_tpu_serde_bytes_total`` zlib+none)
  across the q3 rounds: every byte the page codec decoded must have been
  attributed to a pull record (framing/page headers make the ledger side
  strictly larger, so a fraction below 1.0 means a producer is not
  recording). Gated direction=up, >= 0.95 acceptance;
- **straggler detection** — a deliberately skewed repartition join on a
  4-worker cluster (every probe row's derived key collapses onto one
  nation key, so one join task receives ~the whole probe side while its
  three stage peers idle): the detector must flag the hot task with a
  transfer-vs-device cause, and must flag NOTHING on the uniform q3 /
  export rounds (``straggler_false_positives`` gated at 0). The skew
  run lowers ``straggler_multiple`` to 2.0 — the sensitivity knob this
  PR registers — because a 4-task stage's median includes startup wall
  the cold tasks spend waiting on the same exchange.

Writes ``FLOW_r01.json`` (folded into TRAJECTORY.json by
``tools/bench_trend.py``'s FLOW family). ``--check`` is the tiny-schema
quick pass: 2-worker cluster only, conservation + zero-false-positive
asserts, no artifact (tiny's sub-``min_elapsed`` tasks can never flag,
so the skew phase would assert nothing it can miss).

Run:    python microbench/flows.py [tpch_schema] [--workers W]
Check:  python microbench/flows.py --check
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MIN_CONSERVATION = 0.95   # the ISSUE acceptance bound
ROUNDS = 3                # q3 repeats (cold round 1, warm rounds after)
SKEW_WORKERS = 4          # >2: a 2-task stage's median caps ratio at 2x
SKEW_MULTIPLE = 2.0       # straggler_multiple for the skew run (see doc)

Q3_SQL = """
select l_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate, l_orderkey limit 10
"""

# wide rows, no aggregate: enough result bytes to cross the spool
# threshold so the spool-write/segment-fetch links light up (bounded by
# key, not LIMIT: a per-worker limit under worker-direct spooling would
# make the returned row count ambiguous)
EXPORT_SQL = ("select o_orderkey, o_custkey, o_totalprice, o_orderdate "
              "from orders where o_orderkey <= {max_key}")

# every o_custkey > 3 collapses onto derived key 1 = one nation key, so
# the hash exchange routes ~the whole probe side to one join task; the
# build side stays unique-keyed (nation), so no output explosion
SKEW_SQL = """
select count(*) as c, sum(n.n_nationkey) as s
from (select case when o_custkey > 3 then 1 else o_custkey end as o_k
      from orders) o
join nation n on o.o_k = n.n_nationkey
"""


def _decode_wire_bytes() -> float:
    """Serde decode-side WIRE bytes (compressed zlib blocks + raw-stored
    none blocks; 'logical' is the uncompressed denominator, not wire)."""
    from trino_tpu.obs import metrics as M

    return (M.SERDE_BYTES.value("decode", "zlib")
            + M.SERDE_BYTES.value("decode", "none"))


def _link_totals() -> dict:
    """``{link: {"bytes", "seconds"}}`` from the process flow ledger."""
    from trino_tpu.obs.flowledger import FLOW_LEDGER

    agg: dict = {}
    for r in FLOW_LEDGER.transfer_rows():
        a = agg.setdefault(r["link"], {"bytes": 0, "seconds": 0.0})
        a["bytes"] += int(r["bytes"])
        a["seconds"] += float(r["seconds"])
    return agg


def _boot(workers: int, prefix: str):
    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    fleet = [WorkerServer(coordinator_url=coord.base_url,
                          node_id=f"{prefix}{i}") for i in range(workers)]
    for w in fleet:
        w.start()
    assert coord.registry.wait_for_workers(workers, timeout=30.0)
    return coord, fleet


def _stop(coord, fleet) -> None:
    for w in fleet:
        w.stop()
    coord.stop()


def run_uniform(schema: str, workers: int) -> dict:
    """Phase 1: uniform q3 rounds (conservation window) + spooled export
    on a 2-worker cluster; no task may flag as a straggler."""
    from trino_tpu.client import dbapi

    coord, fleet = _boot(workers, "flow")
    try:
        cur = dbapi.connect(coordinator_url=coord.base_url,
                            catalog="tpch", schema=schema).cursor()
        pull0 = _link_totals().get("exchange-pull", {}).get("bytes", 0)
        serde0 = _decode_wire_bytes()
        false_positives = 0
        wall = []
        for _ in range(ROUNDS):
            t0 = time.perf_counter()
            cur.execute(Q3_SQL)
            cur.fetchall()
            wall.append(time.perf_counter() - t0)
            flows = (cur.stats or {}).get("flows") or {}
            false_positives += int(flows.get("stragglers") or 0)
        pull_delta = _link_totals().get("exchange-pull", {}).get("bytes", 0) - pull0
        serde_delta = _decode_wire_bytes() - serde0
        conservation = (min(1.0, pull_delta / serde_delta)
                        if serde_delta > 0 else 1.0)

        # spooled export: result segments written worker-side and fetched
        # by the client (spool-write + segment-fetch + the drain tail)
        max_key = 600_000 if schema != "tiny" else 60_000
        spool = dbapi.connect(
            coordinator_url=coord.base_url, catalog="tpch", schema=schema,
            spooled_results_enabled="true",
            spooled_results_threshold_bytes="1024",
            spooled_results_segment_bytes="65536").cursor()
        spool.execute(EXPORT_SQL.format(max_key=max_key))
        nrows = len(spool.fetchall())
        assert nrows > 0
        assert (spool.stats or {}).get("spooled"), "export never spooled"
        flows = (spool.stats or {}).get("flows") or {}
        false_positives += int(flows.get("stragglers") or 0)

        # the announce loop (0.5 s cadence) must deliver worker flow rows
        # before the coordinator-side table read
        time.sleep(1.5)
        cur.execute("select link, sum(bytes) from system.runtime.transfers "
                    "group by link")
        table_links = {r[0]: int(r[1]) for r in cur.fetchall()}
        cur.execute("select count(*) from system.runtime.stragglers")
        false_positives += int(cur.fetchall()[0][0])
        return {
            "warm_q3_seconds": round(min(wall), 4),
            "conservation_fraction": round(conservation, 4),
            "exchange_pull_bytes": int(pull_delta),
            "serde_decode_wire_bytes": int(serde_delta),
            "straggler_false_positives": false_positives,
            "table_links": table_links,
        }
    finally:
        _stop(coord, fleet)


def run_skew(schema: str) -> dict:
    """Phase 2: the skewed repartition join on a 4-worker cluster; the
    hot join task must flag with a transfer-vs-device cause.

    Runs the query TWICE: the cold round compiles the join kernel on
    every task, so elapsed is compile-uniform (~5 s each) and hides the
    skew; the warm round hits the compile cache and the hot task's
    elapsed is pure data (observed ~4-5x its stage median)."""
    from trino_tpu.client import dbapi

    coord, fleet = _boot(SKEW_WORKERS, "skew")
    try:
        # join_max_broadcast_rows=1 forces the repartition path: a 25-row
        # build side would otherwise broadcast and the probe would never
        # cross the hash exchange
        cur = dbapi.connect(coordinator_url=coord.base_url,
                            catalog="tpch", schema=schema,
                            join_max_broadcast_rows=1,
                            straggler_multiple=SKEW_MULTIPLE).cursor()
        for _ in range(2):
            cur.execute(SKEW_SQL)
            rows = cur.fetchall()
            assert rows and int(rows[0][0]) > 0, rows
        cur2 = dbapi.connect(coordinator_url=coord.base_url,
                             catalog="tpch", schema=schema).cursor()
        cur2.execute("select task_id, ratio, cause, elapsed_seconds, "
                     "stage_median_seconds from system.runtime.stragglers")
        flagged = cur2.fetchall()
        top = max(flagged, key=lambda r: float(r[1]), default=None)
        cause = top[2] if top is not None else None
        return {
            "flagged": bool(flagged),
            "cause": cause,
            "cause_ok": cause in ("transfer-bound", "device-bound"),
            "ratio": round(float(top[1]), 2) if top is not None else None,
            "hot_elapsed_s": round(float(top[3]), 3) if top else None,
            "stage_median_s": round(float(top[4]), 3) if top else None,
            "multiple": SKEW_MULTIPLE,
            "flagged_tasks": len(flagged),
        }
    finally:
        _stop(coord, fleet)


def main() -> None:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    check_mode = "--check" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    schema = args[0] if args else ("tiny" if check_mode else "sf1")

    uniform = run_uniform(schema, workers=2)
    assert uniform["conservation_fraction"] >= MIN_CONSERVATION, (
        f"exchange-pull conservation {uniform['conservation_fraction']} "
        f"below the {MIN_CONSERVATION} acceptance bound "
        f"(pull={uniform['exchange_pull_bytes']} "
        f"serde={uniform['serde_decode_wire_bytes']})")
    assert uniform["straggler_false_positives"] == 0, (
        f"uniform rounds flagged "
        f"{uniform['straggler_false_positives']} straggler(s)")
    assert uniform["table_links"], "system.runtime.transfers came up empty"

    if check_mode:
        print(json.dumps(uniform, indent=2))
        print(f"flows-check ok: conservation "
              f"{uniform['conservation_fraction']}, links "
              f"{sorted(uniform['table_links'])}, 0 false positives")
        return

    straggler = run_skew(schema)

    # per-link throughput from the whole run (both clusters share the
    # process-global ledger; seconds are per-link transfer wall)
    links = {}
    for link, a in sorted(_link_totals().items()):
        links[link] = {
            "mb": round(a["bytes"] / 1e6, 3),
            "mb_s": (round(a["bytes"] / a["seconds"] / 1e6, 2)
                     if a["seconds"] > 0 else None),
        }
    for need in ("exchange-pull", "staging-transfer", "spool-write",
                 "segment-fetch", "client-drain"):
        assert need in links, (
            f"link {need} never recorded (have {sorted(links)})")

    report = {
        "round": 1,
        "tpch_schema": schema,
        "workers": 2,
        "skew_workers": SKEW_WORKERS,
        "q3_rounds": ROUNDS,
        "warm_q3_seconds": uniform["warm_q3_seconds"],
        "links": links,
        "conservation_fraction": uniform["conservation_fraction"],
        "straggler_false_positives": uniform["straggler_false_positives"],
        "straggler": straggler,
    }
    print(json.dumps(report, indent=2))
    assert straggler["flagged"], "skewed join's hot task never flagged"
    assert straggler["cause_ok"], f"unexpected cause {straggler['cause']}"
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "FLOW_r01.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}: conservation "
          f"{report['conservation_fraction']}, straggler "
          f"{straggler['cause']} @ {straggler['ratio']}x, "
          f"{len(links)} links")


if __name__ == "__main__":
    main()
