"""Join-kernel microbench: dense / legacy sort-merge / fused tier on TPU.

Writes KERNELS_r06.json: per-size timings for the unique-key join kernels
(ops/join.py dense_* and build_side/probe_unique baselines, the PR 8
fused tier in ops/fused_join.py, the warm sorted-build merge, and — on
TPU — the Pallas tiled merge), plus the overlapped-exchange case on
multi-device meshes. ``--check`` runs the CPU tier-selection regression
guard instead (see :func:`check`).

Why there is no Pallas linear-probe hash table here (the round-4 verdict's
item 3, reference ``operator/FlatHash.java:42`` / ``join/PagesHash``):
measured on this v5e through the fori harness, EVERY per-element
random-access primitive — gather, scatter, scatter-add, with random OR
sorted indices — runs at ~7 ns/element (~1 GB/s over int64 rows), while
``lax.sort`` runs a 4M-row key sort in 7.6 ms (~2-4 GB/s effective) and
pure streaming passes run at 50+ GB/s. The TPU VPU has no vectorized
random access into VMEM or HBM (a hash-probe inner loop is exactly that),
so an open-addressing table in Pallas bottoms out on the same scalar
access floor and cannot approach the reference's CPU SWAR probe design
point. The hardware-appropriate strategy is the one the engine uses:
sort/merge-rank formulations for general keys, the direct-address table
(one scatter + one bounded gather) where TPC-style dense integer keys make
the identity map a perfect hash, and touching fewer rows in the first
place (in-program dynamic filtering + stats-sized compaction).

Run: python microbench/join_kernels.py  (TPU; ~2 min warm cache)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

# self-locate the repo: PYTHONPATH must NOT be used for TPU runs (the env
# var propagates to the axon tunnel's compile-helper subprocess and breaks
# its backend registration; sys.path edits stay in-process)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

jax.config.update("jax_enable_x64", True)
_CACHE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache")
if os.path.isdir(os.path.dirname(_CACHE)):
    jax.config.update("jax_compilation_cache_dir", _CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _harness(op, n_args):
    """fori-loop repetition harness (bench.py pattern): i-dependent
    never-taken perturbation defeats hoisting, output folding defeats DCE;
    per-op seconds = (t_2K - t_K) / K — sync/dispatch noise cancels."""

    def fn(args, k):
        def step(i, carry):
            acc, a = carry
            x = a[0]
            a0 = (x.at[0].set(jnp.where(i < 0, x[0] + 1, x[0])),) + a[1:]
            r = op(*a0)
            tot = jnp.float32(0)
            for o in (r if isinstance(r, tuple) else (r,)):
                tot = tot + jnp.sum(o.astype(jnp.float32))
            return acc + tot, a

        acc, _ = jax.lax.fori_loop(0, k, step, (jnp.float32(0), args))
        return acc

    return jax.jit(fn)


def measure(op, args, k=16):
    f = _harness(op, len(args))
    np.asarray(f(args, 1))
    t0 = time.time(); np.asarray(f(args, k)); ta = time.time() - t0
    t0 = time.time(); np.asarray(f(args, 2 * k)); tb = time.time() - t0
    return max((tb - ta) / k, 1e-9)


def join_cases(n_probe: int, n_build: int, with_pallas: bool = True, k: int = 16):
    """Per-kernel timings for one (probe, build) size: the two r05
    baselines (dense direct-address, legacy SortedBuild sort-merge) plus
    the PR 8 fused tier — ``fused_lookup`` (one combined sort, no
    SortedBuild intermediate; the cost-gate default for non-dense keys),
    ``merge_warm_build`` (probe-only merge against a PRE-SORTED build,
    the device build-cache warm shape), and ``merge_warm_pallas`` (the
    same shape through the Pallas tiled-merge kernel; TPU only — the
    interpreter would dominate the timing off-TPU)."""
    import jax as _jax

    from trino_tpu.ops import fused_join as FJ
    from trino_tpu.ops import join as J

    rng = np.random.default_rng(7)
    span = n_build
    bkeys = jnp.asarray(rng.permutation(span).astype(np.int64))
    pkeys = jnp.asarray(rng.integers(0, span, size=n_probe).astype(np.int64))
    payload = jnp.asarray(rng.integers(0, 1 << 30, size=n_build).astype(np.int64))

    def dense(pk, bk, pay):
        table = J.dense_unique_table((bk, None), None, 0, span)
        rows, matched = J.dense_probe_unique(table, (pk, None), 0)
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    def sortmerge(pk, bk, pay):
        build = J.build_side([(bk, None)], None)
        rows, matched = J.probe_unique(build, [(pk, None)])
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    def fused(pk, bk, pay):
        rows, matched = FJ.fused_probe_unique([(bk, None)], None, [(pk, None)])
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    # warm-build shape: the build sort happened ONCE (device build cache /
    # presorted column); steady state pays only the probe-side merge
    warm = J.build_side([(bkeys, None)], None)

    def merge_warm(pk, bc, br, bl, pay):
        sb = J.SortedBuild([bc], br, bl, True)
        rows, matched = FJ.merge_sorted_build(sb, [(pk, None)])
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    cases = [
        ("dense_lookup", dense, (pkeys, bkeys, payload)),
        ("sortmerge_lookup", sortmerge, (pkeys, bkeys, payload)),
        ("fused_lookup", fused, (pkeys, bkeys, payload)),
        ("merge_warm_build", merge_warm,
         (pkeys, warm.cols[0], warm.rows, warm.live, payload)),
    ]
    if with_pallas and _jax.default_backend() == "tpu":
        # int32 keys (span << 2^31 proves the sentinel unreachable)
        b32 = warm.cols[0].astype(jnp.int32)
        p32 = pkeys.astype(jnp.int32)

        def merge_pallas_case(pk, bc, br, bl, pay):
            sb = J.SortedBuild([bc], br, bl, True)
            rows, matched = FJ.merge_sorted_build(
                sb, [(pk, None)], use_pallas=True)
            return pay[jnp.clip(rows, 0, n_build - 1)], matched

        cases.append(("merge_warm_pallas", merge_pallas_case,
                      (p32, b32, warm.rows, warm.live, payload)))

    out = {}
    for name, op, args in cases:
        per = measure(op, args, k=k)
        out[name] = {
            "seconds": round(per, 6),
            "probe_rows_per_sec": round(n_probe / per),
            "gbytes_per_sec_int64": round(n_probe * 8 / per / 1e9, 3),
        }
    base = out["sortmerge_lookup"]["seconds"]
    for name in ("fused_lookup", "merge_warm_build", "merge_warm_pallas"):
        if name in out:
            out[name]["vs_sortmerge"] = round(base / out[name]["seconds"], 3)
    return out


def overlap_case(n_per_shard: int = 1 << 18, blocks: int = 4):
    """Overlapped vs one-shot exchange+probe on the local mesh: each shard
    hash-exchanges its rows, then probes a replicated dense build. With
    >1 device the overlapped variant pipelines the all_to_all of send
    block k+1 against probe compute on block k
    (parallel/exchange.repartition_page_overlapped). Returns None on a
    single-device mesh (no exchange to overlap)."""
    import jax as _jax
    from jax.sharding import Mesh, PartitionSpec as PSpec

    from trino_tpu import types as T
    from trino_tpu.data.page import Column, Page
    from trino_tpu.ops import join as J
    from trino_tpu.parallel import exchange

    devs = _jax.devices()
    ndev = len(devs)
    if ndev < 2:
        return None
    mesh = Mesh(np.array(devs), ("d",))
    rng = np.random.default_rng(11)
    span = 1 << 16
    keys = rng.integers(0, span, size=(ndev, n_per_shard)).astype(np.int64)
    bkeys = rng.permutation(span).astype(np.int64)  # replicated build
    capacity = 2 * n_per_shard  # 2x-uniform headroom

    def _shard_map(f, in_specs, out_specs):
        if hasattr(_jax, "shard_map"):
            return _jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                                  out_specs=out_specs, check_vma=False)
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)

    def probe(recv: Page, table) -> Page:
        rows, matched = J.dense_probe_unique(
            table, (recv.columns[0].values, None), 0)
        hit = Column(T.BIGINT, rows.astype(jnp.int64))
        sel = matched if recv.sel is None else (recv.sel & matched)
        return Page([recv.columns[0], hit], sel)

    def body(k, bk, n_blocks: int):
        page = Page([Column(T.BIGINT, k.reshape(-1))], None)
        table = J.dense_unique_table((bk.reshape(-1), None), None, 0, span)
        if n_blocks <= 1:
            recv, _ovf = exchange.repartition_page(
                page, [0], ndev, capacity, "d")
            out = probe(recv, table)
        else:
            out, _ovf = exchange.repartition_page_overlapped(
                page, [0], ndev, capacity, "d", n_blocks,
                lambda lp: probe(lp, table))
        tot = jnp.sum(jnp.where(
            out.sel, out.columns[1].values, 0)) if out.sel is not None \
            else jnp.sum(out.columns[1].values)
        return tot[None]

    res = {}
    for label, n_blocks in (("exchange_then_compute", 1),
                            (f"overlapped_{blocks}_blocks", blocks)):
        fn = _shard_map(lambda k, bk, nb=n_blocks: body(k, bk, nb),
                        (PSpec("d"), PSpec()), PSpec("d"))
        per = measure(lambda k, bk: fn(k, bk),
                      (jnp.asarray(keys), jnp.asarray(bkeys)), k=8)
        res[label] = {
            "seconds": round(per, 6),
            "rows_per_sec": round(ndev * n_per_shard / per),
        }
    one = res["exchange_then_compute"]["seconds"]
    res[f"overlapped_{blocks}_blocks"]["vs_one_shot"] = round(
        one / res[f"overlapped_{blocks}_blocks"]["seconds"], 3)
    res["devices"] = ndev
    return res


def check(margin: float = 1.5, attempts: int = 3) -> int:
    """CPU-runnable tier-selection regression guard (``--check``):

    1. the cost gate must still pick the dense direct-address path for a
       dense-keyed build and the fused tier for a sparse one (selection
       drift = silent perf loss);
    2. on the sparse case — where the gate selects the fused tier — the
       fused kernel must not run more than ``margin`` slower than the
       legacy sortmerge baseline it replaced (best of ``attempts`` to
       absorb CI timing noise; the dense kernel is also reported for the
       record).

    Returns a process exit code (0 ok, 1 regression).
    """
    from trino_tpu import Session
    from trino_tpu.data.page import Column, Page
    from trino_tpu import types as T
    from trino_tpu.exec.executor import Executor
    from trino_tpu.obs import metrics as M
    from trino_tpu.ops import fused_join as FJ
    from trino_tpu.ops import join as J
    from trino_tpu.sql.planner import plan as P

    rng = np.random.default_rng(3)
    n_probe, n_build = 1 << 17, 1 << 14
    # --- selection: dense-keyed build -> dense tier
    ex = Executor(Session())
    dense_b = Page([Column(T.BIGINT, jnp.arange(n_build, dtype=jnp.int64),
                           vrange=(0, n_build - 1))])
    probe_p = Page([Column(
        T.BIGINT,
        jnp.asarray(rng.integers(0, n_build, n_probe).astype(np.int64)),
        vrange=(0, n_build - 1))])
    node = P.JoinNode(join_type="inner", left=None, right=None,
                      left_keys=[0], right_keys=[0], right_unique=True)
    before = {t: M.FUSED_JOIN_SELECTIONS.value(t)
              for t in ("dense", "fused")}
    ex.lookup_join(node, probe_p, dense_b)
    if M.FUSED_JOIN_SELECTIONS.value("dense") != before["dense"] + 1:
        print("CHECK FAIL: dense-keyed build no longer selects the dense "
              "tier", file=sys.stderr)
        return 1
    # --- selection + timing: sparse build -> fused tier
    sparse_span = 1 << 40  # far beyond DENSE_SPAN_MAX
    bkeys_np = rng.choice(sparse_span, size=n_build, replace=False).astype(np.int64)
    pk_np = np.concatenate([
        rng.choice(bkeys_np, size=n_probe // 2),
        rng.integers(0, sparse_span, size=n_probe - n_probe // 2),
    ]).astype(np.int64)
    sparse_b = Page([Column(T.BIGINT, jnp.asarray(bkeys_np),
                            vrange=(0, sparse_span))])
    sparse_p = Page([Column(T.BIGINT, jnp.asarray(pk_np),
                            vrange=(0, sparse_span))])
    ex.lookup_join(node, sparse_p, sparse_b)
    if M.FUSED_JOIN_SELECTIONS.value("fused") != before["fused"] + 1:
        print("CHECK FAIL: sparse-keyed build no longer selects the fused "
              "tier", file=sys.stderr)
        return 1
    bk = jnp.asarray(bkeys_np)
    pk = jnp.asarray(pk_np)
    pay = jnp.asarray(rng.integers(0, 1 << 30, n_build).astype(np.int64))

    def fused(p, b, w):
        rows, matched = FJ.fused_probe_unique([(b, None)], None, [(p, None)])
        return w[jnp.clip(rows, 0, n_build - 1)], matched

    def legacy(p, b, w):
        build = J.build_side([(b, None)], None)
        rows, matched = J.probe_unique(build, [(p, None)])
        return w[jnp.clip(rows, 0, n_build - 1)], matched

    t_fused = min(measure(fused, (pk, bk, pay), k=4) for _ in range(attempts))
    t_legacy = min(measure(legacy, (pk, bk, pay), k=4) for _ in range(attempts))
    ratio = t_fused / t_legacy
    print(json.dumps({
        "check": "join-kernel-regression",
        "fused_seconds": round(t_fused, 6),
        "sortmerge_seconds": round(t_legacy, 6),
        "fused_over_sortmerge": round(ratio, 3),
        "margin": margin,
        "ok": ratio <= margin,
    }))
    if ratio > margin:
        print(f"CHECK FAIL: fused tier {ratio:.2f}x slower than the legacy "
              f"sortmerge baseline it replaced (margin {margin}x)",
              file=sys.stderr)
        return 1
    return 0


def _devices_with_retry(attempts: int = 4):
    """First device touch through the tunnel can fail transiently."""
    for i in range(attempts):
        try:
            return jax.devices()
        except RuntimeError:
            if i == attempts - 1:
                raise
            time.sleep(5 * (i + 1))


def main():
    if "--check" in sys.argv:
        raise SystemExit(check())
    sizes = [(1 << 20, 1 << 19), (1 << 24, 1 << 22)]  # 1M and 16M probes
    result = {
        "device": str(_devices_with_retry()[0]),
        "note": ("fused tier (ops/fused_join.py): one combined build+probe"
                 " sort replacing sort(build)+sort(N)+sort(N)+gather;"
                 " merge_warm_* = pre-sorted build (device build cache)."
                 " The pallas kernel here is the tiled two-pointer MERGE"
                 " over sorted blocks — NOT a hash probe: the measured"
                 " ~7ns/element random-access floor still rules out any"
                 " probe-per-element design; see module docstring"),
        "cases": {},
    }
    for n_probe, n_build in sizes:
        label = f"probe={n_probe>>20}M,build={max(n_build>>20,1)}M" if n_probe >= (1 << 20) \
            else f"probe={n_probe},build={n_build}"
        print(f"[kernels] {label} ...", file=sys.stderr, flush=True)
        result["cases"][label] = join_cases(n_probe, n_build)
    print("[kernels] overlapped exchange ...", file=sys.stderr, flush=True)
    ov = overlap_case()
    result["overlapped_exchange"] = ov if ov is not None else (
        "skipped: single-device mesh")
    out_path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "KERNELS_r06.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
