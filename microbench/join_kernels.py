"""Join-kernel microbench: dense direct-address vs sort-merge on real TPU.

Writes KERNELS_r05.json: per-size timings for the two unique-key join
kernels (ops/join.py dense_* vs build_side/probe_unique) plus the primitive
ops that bound any alternative design.

Why there is no Pallas linear-probe hash table here (the round-4 verdict's
item 3, reference ``operator/FlatHash.java:42`` / ``join/PagesHash``):
measured on this v5e through the fori harness, EVERY per-element
random-access primitive — gather, scatter, scatter-add, with random OR
sorted indices — runs at ~7 ns/element (~1 GB/s over int64 rows), while
``lax.sort`` runs a 4M-row key sort in 7.6 ms (~2-4 GB/s effective) and
pure streaming passes run at 50+ GB/s. The TPU VPU has no vectorized
random access into VMEM or HBM (a hash-probe inner loop is exactly that),
so an open-addressing table in Pallas bottoms out on the same scalar
access floor and cannot approach the reference's CPU SWAR probe design
point. The hardware-appropriate strategy is the one the engine uses:
sort/merge-rank formulations for general keys, the direct-address table
(one scatter + one bounded gather) where TPC-style dense integer keys make
the identity map a perfect hash, and touching fewer rows in the first
place (in-program dynamic filtering + stats-sized compaction).

Run: python microbench/join_kernels.py  (TPU; ~2 min warm cache)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

# self-locate the repo: PYTHONPATH must NOT be used for TPU runs (the env
# var propagates to the axon tunnel's compile-helper subprocess and breaks
# its backend registration; sys.path edits stay in-process)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

jax.config.update("jax_enable_x64", True)
_CACHE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache")
if os.path.isdir(os.path.dirname(_CACHE)):
    jax.config.update("jax_compilation_cache_dir", _CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _harness(op, n_args):
    """fori-loop repetition harness (bench.py pattern): i-dependent
    never-taken perturbation defeats hoisting, output folding defeats DCE;
    per-op seconds = (t_2K - t_K) / K — sync/dispatch noise cancels."""

    def fn(args, k):
        def step(i, carry):
            acc, a = carry
            x = a[0]
            a0 = (x.at[0].set(jnp.where(i < 0, x[0] + 1, x[0])),) + a[1:]
            r = op(*a0)
            tot = jnp.float32(0)
            for o in (r if isinstance(r, tuple) else (r,)):
                tot = tot + jnp.sum(o.astype(jnp.float32))
            return acc + tot, a

        acc, _ = jax.lax.fori_loop(0, k, step, (jnp.float32(0), args))
        return acc

    return jax.jit(fn)


def measure(op, args, k=16):
    f = _harness(op, len(args))
    np.asarray(f(args, 1))
    t0 = time.time(); np.asarray(f(args, k)); ta = time.time() - t0
    t0 = time.time(); np.asarray(f(args, 2 * k)); tb = time.time() - t0
    return max((tb - ta) / k, 1e-9)


def join_cases(n_probe: int, n_build: int):
    from trino_tpu.ops import join as J

    rng = np.random.default_rng(7)
    span = n_build
    bkeys = jnp.asarray(rng.permutation(span).astype(np.int64))
    pkeys = jnp.asarray(rng.integers(0, span, size=n_probe).astype(np.int64))
    payload = jnp.asarray(rng.integers(0, 1 << 30, size=n_build).astype(np.int64))

    def dense(pk, bk, pay):
        table = J.dense_unique_table((bk, None), None, 0, span)
        rows, matched = J.dense_probe_unique(table, (pk, None), 0)
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    def sortmerge(pk, bk, pay):
        build = J.build_side([(bk, None)], None)
        rows, matched = J.probe_unique(build, [(pk, None)])
        return pay[jnp.clip(rows, 0, n_build - 1)], matched

    out = {}
    for name, op in [("dense_lookup", dense), ("sortmerge_lookup", sortmerge)]:
        per = measure(op, (pkeys, bkeys, payload))
        out[name] = {
            "seconds": round(per, 6),
            "probe_rows_per_sec": round(n_probe / per),
            "gbytes_per_sec_int64": round(n_probe * 8 / per / 1e9, 3),
        }
    return out


def _devices_with_retry(attempts: int = 4):
    """First device touch through the tunnel can fail transiently."""
    for i in range(attempts):
        try:
            return jax.devices()
        except RuntimeError:
            if i == attempts - 1:
                raise
            time.sleep(5 * (i + 1))


def main():
    sizes = [(1 << 20, 1 << 19), (1 << 24, 1 << 22)]  # 1M and 16M probes
    result = {
        "device": str(_devices_with_retry()[0]),
        "note": ("no pallas hash-probe variant: measured random-access floor"
                 " ~7ns/element on v5e makes any probe-per-element design"
                 " slower than the sort/dense formulations; see module"
                 " docstring"),
        "cases": {},
    }
    for n_probe, n_build in sizes:
        label = f"probe={n_probe>>20}M,build={max(n_build>>20,1)}M" if n_probe >= (1 << 20) \
            else f"probe={n_probe},build={n_build}"
        print(f"[kernels] {label} ...", file=sys.stderr, flush=True)
        result["cases"][label] = join_cases(n_probe, n_build)
    out_path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "KERNELS_r05.json")
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
