"""Skewed-key repartition join microbench: adaptation on vs off.

One hot key owns ``HOT_FRACTION`` of the probe side, so the hash
exchange's static per-(shard, partition) block guess — ~2x the uniform
share (``sql/planner/stats.exchange_capacity``) — understates the hot
partition's real block by ~n_devices/2 and the SPMD run loop pays the
double-and-recompile spiral until the bucket catches up. With
``adaptive_capacity_reseed`` the send blocks are priced from the STAGED
key histograms (``trino_tpu/adaptive/reseed.py``), the hot partition gets
its true capacity on the first compile, and the regrowth loop never
fires.

Reports steady-state rows/sec (probe rows / wall, post-compile) and the
capacity-recompile count for both modes; writes SKEWJOIN.json next to the
other bench artifacts.

Run: python microbench/skew_join.py [n_rows]  (CPU mesh or real TPU)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# self-locate the repo (see microbench/join_kernels.py: PYTHONPATH must
# not be used on TPU runs)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HOT_FRACTION = 0.85
N_DEVICES = 8
STEADY_RUNS = 3


# the host-platform device count must be configured BEFORE jax
# initializes its backend — set it at import time (conftest.py pattern)
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={N_DEVICES}").strip()


def _mesh():
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    assert len(devs) >= N_DEVICES, "need an 8-device mesh (CPU or TPU)"
    return Mesh(np.array(devs[:N_DEVICES]), ("d",))


def _make_tables(session, n_rows: int):
    """Probe with one hot key owning HOT_FRACTION of the rows; build with
    every key exactly once (an expansion join on the hot key would be
    quadratic — the skew story here is the EXCHANGE block, as in a
    fact-to-dimension repartition join)."""
    from trino_tpu import types as T

    rng = np.random.default_rng(7)
    n_hot = int(n_rows * HOT_FRACTION)
    keys = np.concatenate([
        np.full(n_hot, 1, dtype=np.int64),
        rng.integers(2, n_rows, size=n_rows - n_hot, dtype=np.int64),
    ])
    rng.shuffle(keys)
    vals = np.arange(n_rows, dtype=np.int64)
    mem = session.catalogs["memory"]
    mem.create_table("sk", "probe", [("k", T.BIGINT), ("v", T.BIGINT)],
                     list(zip(keys.tolist(), vals.tolist())))
    build_keys = np.unique(keys)
    mem.create_table("sk", "build", [("k", T.BIGINT), ("w", T.BIGINT)],
                     [(int(k), int(k) * 3) for k in build_keys])
    return len(keys)


SQL = ("select count(*) c, sum(p.v + b.w) s "
       "from memory.sk.probe p, memory.sk.build b where p.k = b.k")


def _run_mode(session, mesh, n_rows: int):
    from trino_tpu.exec.query import plan_sql
    from trino_tpu.parallel.spmd import DistributedQuery

    root = plan_sql(session, SQL)
    t0 = time.perf_counter()
    dq = DistributedQuery.build(session, root, mesh)
    first = dq.run().to_pylist()
    cold_s = time.perf_counter() - t0
    t1 = time.perf_counter()
    for _ in range(STEADY_RUNS):
        out = dq.run().to_pylist()
    steady_s = (time.perf_counter() - t1) / STEADY_RUNS
    assert out == first
    return {
        "recompiles": dq.recompiles,
        "cold_s": round(cold_s, 4),
        "steady_s": round(steady_s, 4),
        "rows_per_s": round(n_rows / steady_s, 1),
        "result": first,
        "xchg_hints": {k: v for k, v in dq.capacity_hints.items()
                       if k.startswith("xchg")},
    }


def main() -> None:
    import jax

    jax.config.update("jax_platforms", os.environ.get("JAX_PLATFORMS", "cpu"))
    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    mesh = _mesh()
    from trino_tpu.client.session import Session
    from trino_tpu.sql.planner import stats as stats_mod

    # force the co-partitioned path so the exchange is the story
    stats_mod.BROADCAST_BUILD_MAX = 64

    base = Session()
    n = _make_tables(base, n_rows)
    off = _run_mode(base, mesh, n)

    on_session = Session({"adaptive_capacity_reseed": True})
    on_session.catalogs = base.catalogs  # same tables
    on = _run_mode(on_session, mesh, n)
    assert on["result"] == off["result"], (on["result"], off["result"])

    report = {
        "n_rows": n,
        "hot_fraction": HOT_FRACTION,
        "n_devices": N_DEVICES,
        "adaptation_off": off,
        "adaptation_on": on,
    }
    print(json.dumps(report, indent=2))
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "SKEWJOIN.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path}: off={off['recompiles']} recompiles "
          f"@ {off['rows_per_s']:.0f} rows/s, on={on['recompiles']} "
          f"recompiles @ {on['rows_per_s']:.0f} rows/s")


if __name__ == "__main__":
    main()
