"""Materialized-view microbench: fresh-MV speedup + the staleness matrix.

Two parts (ROADMAP item 5b acceptance; ISSUE 15):

- **speedup** — the TPC-H q3-shaped join+aggregate on the tpch generator
  catalog, base vs fresh-MV-substituted. Both arms run EMBEDDED (no
  result cache exists in front — "result cache cold" holds by
  construction) with the device cache on and warm: the base arm re-pays
  the full join+aggregate device time per run, the MV arm scans the
  precomputed storage table (pre-staged into the warm-HBM tier by the
  REFRESH). Acceptance: ``speedup >= 5`` at the full scale
  (``MIN_SPEEDUP_FULL``).
- **staleness matrix** — the same q3 shape over MUTABLE memory-catalog
  copies: after each of INSERT / UPDATE / DELETE / DROP+recreate on a
  base table, substitution must be SUPPRESSED (registry hit count does
  not move) and the fallback rows must be BIT-IDENTICAL to the base
  query's (substitution forced off); a REFRESH then flips
  fallback -> substituted again. Any substitution while stale counts in
  ``incorrect_freshness_substitutions`` and fails the run.

Writes ``MATVIEW_r01.json`` (folded into TRAJECTORY.json by
``tools/bench_trend.py``). ``--check`` runs the tiny-schema quick pass
as the tier-1 regression gate
(tests/test_matview.py::test_matview_bench_check) with a lower speedup
floor for CI headroom.

Run: python microbench/matview.py [tpch_schema]   (default sf1)
     python microbench/matview.py --check         (quick gate, tiny)
"""
from __future__ import annotations

import json
import os
import sys
import time

# self-locate the repo (PYTHONPATH must not be used on TPU runs)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MIN_SPEEDUP_FULL = 5.0   # the r01 acceptance bound (sf1)
MIN_SPEEDUP_CHECK = 3.0  # quick-gate floor (tiny schema, CI headroom)
RUNS = 3                 # timed repeats per arm (best-of)

Q3_AGG = """
select l_orderkey, o_orderdate, o_shippriority,
       sum(l_extendedprice * (1 - l_discount)) as revenue
from {customer}, {orders}, {lineitem}
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
"""
Q3_TAIL = " order by revenue desc, o_orderdate, l_orderkey limit 10"


def _q3(**tables) -> str:
    return Q3_AGG.format(**tables)


def _best_of(session, sql: str, runs: int = RUNS) -> float:
    best = float("inf")
    for _ in range(runs):
        t0 = time.perf_counter()
        session.execute(sql)
        best = min(best, time.perf_counter() - t0)
    return best


def _mv_hits(session) -> int:
    return sum(mv.hits for mv in session.matviews.snapshot())


def run_speedup(schema: str) -> dict:
    """Part 1: base vs fresh-MV q3 on the tpch catalog (immutable base
    => the view stays fresh; storage falls back to the memory catalog)."""
    from trino_tpu.client.session import Session

    tables = {"customer": "customer", "orders": "orders",
              "lineitem": "lineitem"}
    base_sql = _q3(**tables) + Q3_TAIL
    session = Session({"catalog": "tpch", "schema": schema,
                       "device_cache_enabled": True})
    base_rows = session.execute(base_sql).rows  # warm compile + devcache
    base_s = _best_of(session, base_sql)
    session.execute("create materialized view q3rev as " + _q3(**tables))
    storage_table = session.matviews.snapshot()[0].storage_table
    hits0 = _mv_hits(session)
    first_rows = session.execute(base_sql).rows
    assert _mv_hits(session) > hits0, "fresh MV did not substitute"
    assert first_rows == base_rows, "substituted rows diverged from base"
    # the REFRESH pre-staged the storage table: the first substituted
    # query must have been served warm (a device-cache hit, zero fresh
    # staged rows for the storage scan)
    from trino_tpu.devcache import DEVICE_CACHE

    warm = [e for e in DEVICE_CACHE.snapshot()
            if e["table"] == storage_table]
    warm_storage_hit = bool(warm) and warm[0]["hits"] >= 1
    hit_s = _best_of(session, base_sql)
    session.execute("drop materialized view q3rev")
    return {
        "base_seconds": round(base_s, 4),
        "hit_seconds": round(hit_s, 4),
        "speedup": round(base_s / hit_s, 2) if hit_s else 0.0,
        "warm_storage_hit": warm_storage_hit,
        "rows": len(base_rows),
    }


def run_staleness_matrix(source_schema: str = "tiny") -> dict:
    """Part 2: INSERT/UPDATE/DELETE/DROP on memory-catalog base tables
    => substitution suppressed + bit-identical fallback => REFRESH =>
    substitution resumes. Returns the matrix record (any incorrect-
    freshness substitution or row divergence raises)."""
    from trino_tpu.client.session import Session

    s = Session({"catalog": "memory", "schema": "default",
                 "device_cache_enabled": True})
    for t in ("customer", "orders", "lineitem"):
        s.execute(f"create table {t} as select * from "
                  f"tpch.{source_schema}.{t}")
    sql = _q3(customer="customer", orders="orders",
              lineitem="lineitem") + Q3_TAIL
    s.execute("create materialized view q3m as " + _q3(
        customer="customer", orders="orders", lineitem="lineitem"))

    def base_truth():
        s.properties["materialized_view_substitution"] = False
        try:
            return s.execute(sql).rows
        finally:
            s.properties["materialized_view_substitution"] = True

    incorrect = 0
    steps = []

    def check_substituted(expect: bool, step: str):
        nonlocal incorrect
        before = _mv_hits(s)
        rows = s.execute(sql).rows
        substituted = _mv_hits(s) > before
        truth = base_truth()
        identical = rows == truth
        if substituted and not expect:
            incorrect += 1
        assert identical, f"{step}: rows diverged from base truth"
        assert substituted == expect, (
            f"{step}: expected substituted={expect}, got {substituted}")
        steps.append({"step": step, "substituted": substituted,
                      "bit_identical": identical})

    check_substituted(True, "fresh")
    mutations = [
        ("insert", "insert into orders select * from orders limit 1"),
        ("update", "update lineitem set l_quantity = l_quantity + 1 "
                   "where l_orderkey = 1"),
        ("delete", "delete from customer where c_custkey = 1"),
        ("drop", None),  # DROP + recreate customer
    ]
    for name, stmt in mutations:
        if name == "drop":
            s.execute("drop table customer")
            s.execute("create table customer as select * from "
                      f"tpch.{source_schema}.customer")
        else:
            s.execute(stmt)
        check_substituted(False, f"{name}-stale")
        s.execute("refresh materialized view q3m")
        check_substituted(True, f"{name}-refreshed")
    s.execute("drop materialized view q3m")
    return {"steps": steps,
            "incorrect_freshness_substitutions": incorrect,
            "stale_fallback_ok": all(st["bit_identical"] for st in steps)}


def run(schema: str, check_mode: bool) -> dict:
    speedup = run_speedup(schema)
    matrix = run_staleness_matrix("tiny")
    report = {
        "round": 1,
        "tpch_schema": schema,
        **speedup,
        **matrix,
        "min_speedup": (MIN_SPEEDUP_CHECK if check_mode
                        else MIN_SPEEDUP_FULL),
    }
    bound = report["min_speedup"]
    assert report["speedup"] >= bound, (
        f"fresh-MV speedup {report['speedup']}x below the {bound}x bound "
        f"(base {report['base_seconds']}s vs hit {report['hit_seconds']}s)")
    assert report["incorrect_freshness_substitutions"] == 0
    assert report["stale_fallback_ok"]
    assert report["warm_storage_hit"], (
        "first post-refresh substituted query was not served from the "
        "warm device cache")
    return report


def main() -> None:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    check_mode = "--check" in sys.argv
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    schema = args[0] if args else ("tiny" if check_mode else "sf1")
    report = run(schema, check_mode)
    print(json.dumps({k: v for k, v in report.items() if k != "steps"},
                     indent=2))
    if check_mode:
        print(f"matview-check ok: base {report['base_seconds']}s, "
              f"hit {report['hit_seconds']}s ({report['speedup']}x), "
              f"staleness matrix {len(report['steps'])} steps clean")
        return
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "MATVIEW_r01.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {out_path}: fresh-MV q3 {report['hit_seconds']}s vs "
          f"base {report['base_seconds']}s ({report['speedup']}x), "
          f"stale fallback bit-identical across "
          f"{len(report['steps'])} matrix steps")


if __name__ == "__main__":
    main()
