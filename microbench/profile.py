"""PROFILE microbench: per-operator dispatch-overhead attribution.

The device profiler (trino_tpu/obs/devprofiler.py) splits every kernel
launch into wall vs device seconds, so wall - device = per-operator
DISPATCH OVERHEAD — the number ROADMAP item 2's fragment megakernels
must beat. This bench records the tracked "before" picture: it boots a
real coordinator + N workers, runs three query shapes with
``device_profiling`` ON (block_until_ready-bracketed device seconds),
reads each query's ``/v1/query/{id}/profile``, and emits per-operator
dispatch-overhead fractions:

- ``point_mix`` — prepared point lookups on the short-query fast path
  (the QPS_r02 serving shape whose 3.3ms p50 is "mostly per-op
  dispatch, not math" — this bench proves it per operator);
- ``q1`` / ``q3`` — TPC-H Q1 and Q3, distributed across the workers.

Attribution denominator: the phase ledger's ``device-execute`` +
``device-staging`` wall (the two phases whose inside the profiler
attributes — TableScan kernel wall covers the staging read). The
acceptance bar is >= 80% of that attributed to named kernels on the
point mix.

The compile-ledger demonstration runs the COMPILED tier embedded (the
server path is eager-only): one CompiledQuery built and run twice must
record a cache ``miss`` then a cache ``hit`` with zero new miss events
— the prepared-EXECUTE reuse story at the jit-cache layer.

Emits ``PROFILE_r01.json`` next to the other bench artifacts.

Run:    python microbench/profile.py [--requests N] [--workers W]
Check:  python microbench/profile.py --check
        (tier-1 quick mode, small N, CPU-runnable, never writes the
        recorded round; asserts kernels attribute the device phases,
        overhead dominates math on the point mix, both system tables
        return rows, and the compile cache hits on the second run)
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

POINT_SQL = ("select o_orderkey, o_totalprice, o_orderstatus "
             "from orders where o_orderkey = ?")
Q1_SQL = """
select l_returnflag, l_linestatus, sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       avg(l_discount) as avg_disc, count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
"""
Q3_SQL = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""
USER = "profile"
# caches OFF for the distributed shapes: a result-cache HIT never
# executes, so its profile has no kernels to attribute
_BASE_PROPS = dict(result_cache_enabled="false",
                   device_cache_enabled="true",
                   device_profiling="true")


def _fetch_profile(coord_url: str, query_id: str) -> dict:
    req = urllib.request.Request(
        f"{coord_url}/v1/query/{query_id}/profile",
        headers={"X-Trino-User": USER})
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read().decode())


def _run_shape(coord_url: str, sqls, fast_path: bool) -> list:
    """Execute each (sql, params) once on its own profiled query and
    return the per-query profile dicts."""
    from trino_tpu.client import dbapi

    props = dict(_BASE_PROPS,
                 short_query_fast_path="true" if fast_path else "false")
    conn = dbapi.connect(coordinator_url=coord_url, user=USER, **props)
    cur = conn.cursor()
    profiles = []
    for sql, params in sqls:
        if params is not None:
            cur.execute(sql, params)
        else:
            cur.execute(sql)
        profiles.append(_fetch_profile(coord_url, conn._client.query_id))
    return profiles


def summarize_shape(profiles) -> dict:
    """Fold per-query profiles into the shape record: per-operator
    launch/wall/device/overhead rollups, the dispatch-overhead fraction
    (overhead wall / kernel wall), and the attribution fraction (kernel
    wall / phase-ledger device-execute + device-staging wall, capped at
    1.0 per query — worker kernels overlap in wall time)."""
    per_op: dict = {}
    attributed = []
    device_execute_s = device_phase_s = kernel_wall_s = 0.0
    for prof in profiles:
        kernels = prof.get("kernels") or []
        phases = (prof.get("timeline") or {}).get("phases") or {}
        dev = float(phases.get("device-execute", 0.0))
        phase = dev + float(phases.get("device-staging", 0.0))
        wall = sum(float(k.get("wallS", 0.0)) for k in kernels)
        device_execute_s += dev
        device_phase_s += phase
        kernel_wall_s += wall
        if phase > 0:
            attributed.append(min(1.0, wall / phase))
        for k in kernels:
            key = (k.get("operator", "?"), k.get("tier", "?"))
            agg = per_op.setdefault(
                key, {"operator": key[0], "tier": key[1], "launches": 0,
                      "wall_s": 0.0, "device_s": 0.0, "overhead_s": 0.0})
            agg["launches"] += int(k.get("launches", 0))
            agg["wall_s"] += float(k.get("wallS", 0.0))
            agg["device_s"] += float(k.get("deviceS", 0.0))
            agg["overhead_s"] += max(
                0.0, float(k.get("wallS", 0.0)) - float(k.get("deviceS", 0.0)))
    ops = []
    for key in sorted(per_op, key=lambda k: -per_op[k]["overhead_s"]):
        a = per_op[key]
        ops.append({
            "operator": a["operator"], "tier": a["tier"],
            "launches": a["launches"],
            "wall_s": round(a["wall_s"], 6),
            "device_s": round(a["device_s"], 6),
            "overhead_s": round(a["overhead_s"], 6),
            "overhead_fraction": round(a["overhead_s"] / a["wall_s"], 4)
            if a["wall_s"] > 0 else None,
        })
    overhead_s = sum(o["overhead_s"] for o in ops)
    return {
        "queries": len(profiles),
        "device_execute_s": round(device_execute_s, 6),
        "device_phase_s": round(device_phase_s, 6),
        "kernel_wall_s": round(kernel_wall_s, 6),
        "kernel_overhead_s": round(overhead_s, 6),
        # mean per-query fraction of the device phases covered by named
        # kernel rows — the >= 80% acceptance bar on the point mix
        "attributed_fraction": round(sum(attributed) / len(attributed), 4)
        if attributed else 0.0,
        # of the attributed kernel wall, how much is dispatch overhead
        # (wall - device) rather than math — the megakernel target
        "dispatch_overhead_fraction": round(overhead_s / kernel_wall_s, 4)
        if kernel_wall_s > 0 else None,
        "per_operator": ops,
    }


def compile_cache_demo() -> dict:
    """The compiled-tier cache-hit demonstration (embedded — the server
    path is eager-only): one CompiledQuery run twice records ``miss``
    then ``hit`` in the compile ledger with ZERO new miss events on the
    repeat — the jit-cache analogue of a second prepared EXECUTE."""
    from trino_tpu import Session
    from trino_tpu.exec.compiled import CompiledQuery
    from trino_tpu.exec.query import plan_sql
    from trino_tpu.obs.devprofiler import DEVICE_PROFILER

    session = Session(properties={"catalog": "tpch", "schema": "tiny"})
    root = plan_sql(session, Q1_SQL)
    cq = CompiledQuery.build(session, root)
    n0 = len(DEVICE_PROFILER.compile_rows())
    cq.run()
    first = DEVICE_PROFILER.compile_rows()[n0:]
    n1 = len(DEVICE_PROFILER.compile_rows())
    cq.run()
    second = DEVICE_PROFILER.compile_rows()[n1:]
    misses = [e for e in first if e.get("cache") == "miss"]
    return {
        "first_run": [e.get("cache") for e in first],
        "second_run": [e.get("cache") for e in second],
        "compile_seconds": round(sum(e.get("compileS", 0.0)
                                     for e in misses), 4),
        "second_run_new_misses": sum(1 for e in second
                                     if e.get("cache") == "miss"),
        "ok": bool(misses) and any(e.get("cache") == "hit" for e in second)
        and not any(e.get("cache") == "miss" for e in second),
    }


def _table_counts(coord_url: str) -> dict:
    """Row counts of the two new system tables over real SQL."""
    from trino_tpu.client import dbapi

    cur = dbapi.connect(coordinator_url=coord_url, user=USER).cursor()
    out = {}
    for table in ("kernels", "compiles"):
        cur.execute(f"select count(*) from system.runtime.{table}")
        out[table] = int(cur.fetchone()[0])
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=24,
                    help="point lookups in the point mix")
    ap.add_argument("--runs", type=int, default=3,
                    help="executions per distributed shape (q1/q3)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--check", action="store_true",
                    help="quick tier-1 mode: small N, relaxed (CI-noise-"
                    "safe) thresholds, never writes the recorded round")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()
    if args.check:
        args.requests, args.runs = 8, 2
    # relaxed bars under --check (shared CI boxes jitter the denominators);
    # the recorded round holds the real acceptance bar
    min_attr = 0.5 if args.check else 0.8
    min_overhead = 0.3 if args.check else 0.5

    from trino_tpu.server.coordinator import CoordinatorServer
    from trino_tpu.server.worker import WorkerServer

    coord = CoordinatorServer()
    coord.start()
    workers = [WorkerServer(coordinator_url=coord.base_url,
                            node_id=f"prof{i}")
               for i in range(args.workers)]
    for w in workers:
        w.start()
    assert coord.registry.wait_for_workers(args.workers, timeout=30.0)
    try:
        # warm the serving path once so the point mix measures
        # steady-state dispatch, not first-touch staging
        _run_shape(coord.base_url, [(POINT_SQL, (7,))], fast_path=True)
        print(f"# point mix: {args.requests} prepared lookups "
              f"(fast path, device_profiling on)", flush=True)
        point_profiles = _run_shape(
            coord.base_url,
            [(POINT_SQL, (1_000_000 + i,)) for i in range(args.requests)],
            fast_path=True)
        point = summarize_shape(point_profiles)
        print(f"  attributed {point['attributed_fraction']:.1%} of the "
              f"device phases; dispatch overhead "
              f"{point['dispatch_overhead_fraction']:.1%} of kernel wall",
              flush=True)
        shapes = {"point_mix": point}
        for name, sql in (("q1", Q1_SQL), ("q3", Q3_SQL)):
            print(f"# {name}: {args.runs} distributed runs", flush=True)
            profs = _run_shape(coord.base_url,
                               [(sql, None)] * args.runs, fast_path=False)
            shapes[name] = summarize_shape(profs)
            print(f"  attributed "
                  f"{shapes[name]['attributed_fraction']:.1%}; overhead "
                  f"{shapes[name]['dispatch_overhead_fraction']:.1%}",
                  flush=True)

        print("# compile ledger: compiled-tier cache hit on rerun",
              flush=True)
        compile_cache = compile_cache_demo()
        print(f"  first {compile_cache['first_run']} -> second "
              f"{compile_cache['second_run']} "
              f"({'ok' if compile_cache['ok'] else 'FAIL'})", flush=True)
        tables = _table_counts(coord.base_url)
        print(f"  system.runtime.kernels {tables['kernels']} rows, "
              f"system.runtime.compiles {tables['compiles']} rows",
              flush=True)

        problems = []
        if point["attributed_fraction"] < min_attr:
            problems.append(
                f"point-mix attribution {point['attributed_fraction']:.1%}"
                f" < {min_attr:.0%}")
        # the QPS_r02 consistency story: on point lookups the math is
        # tiny, so dispatch overhead must dominate the kernel wall
        if (point["dispatch_overhead_fraction"] or 0) < min_overhead:
            problems.append(
                "point-mix dispatch overhead "
                f"{point['dispatch_overhead_fraction']} < {min_overhead} "
                "(overhead should dominate math on point lookups)")
        for name in ("q1", "q3"):
            if not shapes[name]["per_operator"]:
                problems.append(f"{name}: no kernel rows attributed")
        if not compile_cache["ok"]:
            problems.append("compile ledger: no miss->hit on rerun")
        if tables["kernels"] <= 0 or tables["compiles"] <= 0:
            problems.append(f"system tables empty: {tables}")

        result = {
            "bench": "profile",
            "round": 1,
            "platform": os.environ.get("JAX_PLATFORMS", "default"),
            "workers": args.workers,
            "device_profiling": True,
            "shapes": shapes,
            "compile_cache": compile_cache,
            "system_tables": tables,
            "ok": not problems,
        }
        out = args.out or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "PROFILE_r01.json")
        if args.check and args.out is None:
            out = None  # quick mode never clobbers the recorded round
        if out:
            with open(out, "w") as f:
                json.dump(result, f, indent=2)
            print(f"wrote {out}", flush=True)
        if problems:
            print("FAIL: " + "; ".join(problems), file=sys.stderr)
            return 1
        print("OK", flush=True)
        return 0
    finally:
        for w in workers:
            w.stop()
        coord.stop()


if __name__ == "__main__":
    sys.exit(main())
