"""Device table cache microbench: cold vs warm staging on TPC-H Q3.

Two parts, both through the compiled tier with the device cache ON:

- **Ratio** (the PR's acceptance bound): TPC-H Q3 against the tpch
  generator catalog — the COLD build pays the real staging pipeline
  (column generation, phase-1 dynamic-filter pruning, host->device
  transfer; the exact tax BENCH_r05 measured at 22.7 s for q3_sf10),
  the WARM build must serve every scan from the warm-HBM pool: zero
  freshly staged rows, 100% hit rate, and warm staging wall <=
  ``WARM_RATIO_MAX`` x cold.
- **Invalidation** (count-based, timing-free): the same q3 shape on
  memory-connector tables; an INSERT moves the connector's
  ``data_version`` and the next build must RE-STAGE the mutated table
  while the untouched dimensions stay warm.

Writes DEVCACHE.json next to the other bench artifacts so the BENCH_r*
trajectory tracks warm-path wins.

Run: python microbench/device_cache.py [tpch_schema]  (default sf0.2;
CPU or TPU)
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

# self-locate the repo (see microbench/join_kernels.py: PYTHONPATH must
# not be used on TPU runs)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

WARM_RATIO_MAX = 0.1  # warm staging must be <= 0.1x cold (acceptance)

Q3 = """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate limit 10
"""

MINI_Q3 = """
select l_orderkey, sum(l_price) as revenue, o_pri
from customer, orders, lineitem
where c_seg = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_day < 700
group by l_orderkey, o_pri
order by revenue desc limit 10
"""


def _build(session, sql):
    from trino_tpu.exec.compiled import CompiledQuery
    from trino_tpu.exec.query import plan_sql

    root = plan_sql(session, sql)
    t0 = time.perf_counter()
    cq = CompiledQuery.build(session, root)
    return cq, time.perf_counter() - t0


def _ratio_part(schema: str) -> dict:
    from trino_tpu.client.session import Session
    from trino_tpu.devcache import DEVICE_CACHE

    DEVICE_CACHE.invalidate_all()
    session = Session({"catalog": "tpch", "schema": schema,
                       "device_cache_enabled": True})
    cold, cold_build_s = _build(session, Q3)
    warm, warm_build_s = _build(session, Q3)
    scans = len(cold.scan_rows)
    return {
        "tpch_schema": schema,
        "scans": scans,
        "staged_rows": int(sum(cold.scan_rows.values())),
        "cold_build_s": round(cold_build_s, 4),
        "cold_staging_s": round(cold.staging_s, 4),
        "warm_build_s": round(warm_build_s, 4),
        "warm_staging_s": round(warm.staging_s, 4),
        "warm_cold_ratio": round(
            warm.staging_s / cold.staging_s, 4) if cold.staging_s else 0.0,
        "hit_rate": round(warm.cache_hits / scans, 4) if scans else 0.0,
        "warm_fresh_staged_rows": warm.fresh_staged_rows,
        "cache_bytes": DEVICE_CACHE.cached_bytes(),
    }


def _invalidation_part(n_lineitem: int = 200_000) -> dict:
    from trino_tpu import types as T
    from trino_tpu.client.session import Session

    rng = np.random.default_rng(11)
    session = Session({"catalog": "memory", "schema": "db",
                       "device_cache_enabled": True})
    mem = session.catalogs["memory"]
    n_cust, n_ord = n_lineitem // 30, n_lineitem // 4
    mem.create_table(
        "db", "customer", [("c_custkey", T.BIGINT), ("c_seg", T.VARCHAR)],
        [(i, "BUILDING" if i % 5 == 0 else "MACHINERY")
         for i in range(n_cust)])
    mem.create_table(
        "db", "orders",
        [("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
         ("o_day", T.BIGINT), ("o_pri", T.BIGINT)],
        [(i, int(rng.integers(0, n_cust)), int(rng.integers(0, 1000)), i % 3)
         for i in range(n_ord)])
    mem.create_table(
        "db", "lineitem",
        [("l_orderkey", T.BIGINT), ("l_price", T.BIGINT)],
        [(int(rng.integers(0, n_ord)), int(rng.integers(1, 1000)))
         for _ in range(n_lineitem)])
    cold, _ = _build(session, MINI_Q3)
    r_cold = cold.run().to_pylist()
    warm, _ = _build(session, MINI_Q3)
    r_warm = warm.run().to_pylist()
    assert r_cold == r_warm, (r_cold, r_warm)
    session.execute("insert into lineitem values (0, 1)")
    after_dml, _ = _build(session, MINI_Q3)
    return {
        "warm_fresh_staged_rows": warm.fresh_staged_rows,
        "warm_hits": warm.cache_hits,
        "after_dml_fresh_staged_rows": after_dml.fresh_staged_rows,
        "after_dml_hits": after_dml.cache_hits,
        "restages_after_dml": after_dml.fresh_staged_rows > 0,
        "dimensions_stay_warm": after_dml.cache_hits >= 1,
    }


def main() -> None:
    import jax

    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))
    schema = sys.argv[1] if len(sys.argv) > 1 else "sf0.2"
    ratio = _ratio_part(schema)
    inval = _invalidation_part()
    report = {"warm_ratio_max": WARM_RATIO_MAX, "ratio": ratio,
              "invalidation": inval}
    print(json.dumps(report, indent=2))
    assert ratio["warm_fresh_staged_rows"] == 0, "warm build transferred rows"
    assert ratio["hit_rate"] == 1.0, f"hit rate {ratio['hit_rate']} != 1.0"
    assert ratio["warm_cold_ratio"] <= WARM_RATIO_MAX, (
        f"warm staging {ratio['warm_staging_s']}s > "
        f"{WARM_RATIO_MAX}x cold {ratio['cold_staging_s']}s")
    assert inval["restages_after_dml"], "DML write did not restore a re-stage"
    assert inval["dimensions_stay_warm"], "DML write flushed unrelated tables"
    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "DEVCACHE.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"wrote {out_path}: warm/cold staging = "
          f"{ratio['warm_staging_s']}s/{ratio['cold_staging_s']}s "
          f"({ratio['warm_cold_ratio']}x), hit rate {ratio['hit_rate']}")


if __name__ == "__main__":
    main()
