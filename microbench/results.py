"""Spooled-results export bench: client-drain MB/s, inline vs spooled.

The workload production data-export jobs actually run: pull a large
table slice through the client protocol. Inline, every result byte
funnels through the coordinator — Python-row materialization + JSON on
a dispatch-plane lane, then a single paged stream to the client. The
spooled protocol (ISSUE 13) hands the client a segment manifest and the
data plane moves to the producers' ``/v1/segment/{id}`` endpoints,
fetched in PARALLEL.

Honest measurement: each configuration boots a FRESH coordinator + N
worker SUBPROCESS cluster (peak RSS is a process-lifetime high-water
mark — reusing one cluster would let the inline run poison the spooled
run's reading), runs one warmup that generates the source columns, then
ONE measured export. Reported per config:

- ``drain_mb_s`` — result megabytes over the result-delivery window.
  The numerator is the SAME for every config: the inline run's
  statement-protocol payload bytes (what an inline client actually has
  to drain for this result). The window is symmetric: the ledger's
  ``result-serialization`` (result page -> rows/segments) plus the
  drain half — inline: the ledger's ``client-drain`` (paged JSON);
  spooled: the client's measured parallel segment fetch+decode wall;
- ``coord_peak_rss_mb`` — the coordinator subprocess's VmHWM after the
  run (the "one export query OOMs the dispatch plane" signal).

Emits ``RESULTS_r01.json`` (folded into TRAJECTORY.json by
tools/bench_trend.py). Acceptance (full mode): spooled >= 3x inline
drain throughput on a >=100MB result with coordinator peak RSS flat
(spooled adds no result-proportional coordinator memory).

Run:    python microbench/results.py [--sf 0.3] [--workers 2]
Check:  python microbench/results.py --check   (tier-1 quick mode:
        tiny schema, asserts spooled/inline row equality + that the
        manifest path engaged; no perf gate, no artifact write)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

EXPORT_COLS = ("l_orderkey, l_partkey, l_suppkey, l_linenumber, "
               "l_quantity, l_extendedprice, l_discount, l_tax")
EXPORT_SQL = f"select {EXPORT_COLS} from lineitem"
# forces generation of every export column worker-side with a tiny
# result, so the measured run sees a warm generator cache in both configs
WARMUP_SQL = ("select max(l_orderkey + l_partkey + l_suppkey + "
              "l_linenumber), max(l_quantity + l_extendedprice + "
              "l_discount + l_tax) from lineitem")

_BOOT = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
         "from trino_tpu.server.{mod} import main; main()")


def _spawn(mod: str, args, env):
    proc = subprocess.Popen(
        [sys.executable, "-c", _BOOT.format(mod=mod), *args],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    deadline = time.monotonic() + 180.0
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if line.strip().startswith("{"):
            break
        if proc.poll() is not None:
            raise RuntimeError(f"{mod} subprocess died during boot")
    if not line.strip():
        proc.terminate()
        raise RuntimeError(f"{mod} subprocess did not report its URL")
    return proc, json.loads(line)


def boot_cluster(workers: int):
    """Coordinator + N workers as real subprocesses (the bench process
    is client-only, so coordinator RSS is honestly attributable)."""
    from trino_tpu.server import wire

    env = dict(os.environ)
    env["TRINO_TPU_INTERNAL_SECRET"] = wire.get_secret()
    env["PYTHONPATH"] = REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env["JAX_PLATFORMS"] = "cpu"
    coord_proc, hello = _spawn("coordinator", ["--port", "0"], env)
    url = hello["url"]
    procs = [coord_proc]
    try:
        for i in range(workers):
            wproc, _ = _spawn(
                "worker",
                ["--coordinator", url, "--node-id", f"res{i}"], env)
            procs.append(wproc)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            try:
                alive = wire.json_request("GET", f"{url}/v1/node",
                                          timeout=5.0)
                if len(alive) >= workers:
                    break
            except Exception:  # noqa: BLE001 — still booting
                pass
            time.sleep(0.2)
        else:
            raise RuntimeError("workers did not register in time")
    except BaseException:
        for p in procs:
            p.terminate()
        raise
    return url, procs


def peak_rss_mb(pid: int) -> float:
    """VmHWM of a subprocess (lifetime peak resident set), in MB."""
    try:
        with open(f"/proc/{pid}/status", encoding="ascii") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def run_config(schema: str, workers: int, spooled: bool,
               fetch_streams: int, threshold: int = 1 << 20) -> dict:
    """One measured export on a fresh subprocess cluster."""
    from trino_tpu.client import dbapi
    from trino_tpu.server import wire

    url, procs = boot_cluster(workers)
    coord_pid = procs[0].pid
    props = {"schema": schema}
    if spooled:
        props.update({
            "spooled_results_enabled": "true",
            "spooled_results_threshold_bytes": str(threshold),
        })
    try:
        conn = dbapi.connect(coordinator_url=url,
                             fetch_streams=fetch_streams, **props)
        cur = conn.cursor()
        cur.execute(WARMUP_SQL)
        t0 = time.perf_counter()
        cur.execute(EXPORT_SQL)
        wall = time.perf_counter() - t0
        rows = cur.rowcount
        client = conn._client
        qid = client.query_id
        # final ledger AFTER the drain completed (the in-band stats block
        # serializes before the last page/acks land)
        timeline = {}
        try:
            info = wire.json_request("GET", f"{url}/v1/query/{qid}",
                                     timeout=10.0)
            timeline = (info["queryStats"].get("timeline") or {}).get(
                "phases", {})
        except Exception:  # noqa: BLE001 — ledger is supplementary
            pass
        checksum = sum(int(r[0]) for r in cur.fetchall()) % (1 << 61)
        return {
            "spooled": bool(spooled),
            "fetch_streams": fetch_streams,
            "rows": rows,
            "row_checksum": checksum,
            "wall_s": round(wall, 3),
            "response_bytes": getattr(client, "response_bytes", 0),
            "spooled_segments": getattr(client, "spooled_segments", 0),
            "spooled_bytes": getattr(client, "spooled_bytes", 0),
            "segment_fetch_s": round(
                getattr(client, "segment_fetch_s", 0.0), 3),
            "ledger_client_drain_s": round(
                float(timeline.get("client-drain", 0.0)), 3),
            "ledger_segment_fetch_s": round(
                float(timeline.get("segment-fetch", 0.0)), 3),
            "ledger_result_serialization_s": round(
                float(timeline.get("result-serialization", 0.0)), 3),
            "spooled_stat": (client.stats or {}).get("spooled"),
            "coord_peak_rss_mb": round(peak_rss_mb(coord_pid), 1),
        }
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10.0)
            except Exception:  # noqa: BLE001 — escalate
                p.kill()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", default="0.4",
                    help="tpch scale factor for the export (schema "
                         "sf<sf>; full mode)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--check", action="store_true",
                    help="tier-1 quick mode: tiny schema, correctness "
                         "only, no artifact write")
    ap.add_argument("--min-speedup", type=float, default=3.0,
                    help="required spooled/inline drain ratio (full mode)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.check:
        schema, threshold = "tiny", 1024
    else:
        schema = "sf" + str(args.sf).replace(".", "_")
        threshold = 1 << 20

    print(f"# export: {EXPORT_SQL.split(' from ')[0]}... from "
          f"tpch.{schema}.lineitem, {args.workers} workers", flush=True)
    inline = run_config(schema, args.workers, spooled=False,
                        fetch_streams=1, threshold=threshold)
    print(f"  inline    : {inline['rows']} rows in {inline['wall_s']}s "
          f"(client-drain {inline['ledger_client_drain_s']}s, coord RSS "
          f"{inline['coord_peak_rss_mb']}MB)", flush=True)
    spooled_s1 = run_config(schema, args.workers, spooled=True,
                            fetch_streams=1, threshold=threshold)
    spooled_s4 = run_config(schema, args.workers, spooled=True,
                            fetch_streams=4, threshold=threshold)
    for label, rec in (("spooled x1", spooled_s1),
                       ("spooled x4", spooled_s4)):
        print(f"  {label}: {rec['rows']} rows in {rec['wall_s']}s "
              f"({rec['spooled_segments']} segments, fetch "
              f"{rec['segment_fetch_s']}s, coord RSS "
              f"{rec['coord_peak_rss_mb']}MB, mode "
              f"{rec['spooled_stat']})", flush=True)

    problems = []
    if not (inline["rows"] == spooled_s1["rows"] == spooled_s4["rows"]):
        problems.append("row-count mismatch between configs")
    if not (inline["row_checksum"] == spooled_s1["row_checksum"]
            == spooled_s4["row_checksum"]):
        problems.append("row-checksum mismatch between configs")
    if not (spooled_s1["spooled_stat"] and spooled_s4["spooled_stat"]):
        problems.append("spooled configs did not use the manifest path")
    if inline["spooled_stat"]:
        problems.append("inline config unexpectedly spooled")

    # the result, measured as what an inline client actually drains
    # (statement-protocol payload bytes); every config's throughput is
    # over this same numerator, so compression and parallel fetch count
    # as spooled wins rather than changing the unit
    result_mb = inline["response_bytes"] / 1e6
    drains = {}
    for key, rec, drain_s in (
            ("inline", inline, inline["ledger_client_drain_s"]),
            ("spooled_s1", spooled_s1, spooled_s1["segment_fetch_s"]),
            ("spooled_s4", spooled_s4, spooled_s4["segment_fetch_s"])):
        # symmetric delivery window: result page -> rows/segments
        # (result-serialization) + the drain half
        rec["drain_s"] = round(
            drain_s + rec["ledger_result_serialization_s"], 3)
        rec["drain_mb_s"] = (round(result_mb / rec["drain_s"], 2)
                             if rec["drain_s"] else 0.0)
        drains[key] = rec["drain_mb_s"]
    speedup = (drains["spooled_s4"] / drains["inline"]
               if drains["inline"] else 0.0)
    rss_delta_mb = round(
        inline["coord_peak_rss_mb"] - spooled_s4["coord_peak_rss_mb"], 1)
    result = {
        "bench": "results",
        "round": 1,
        "platform": os.environ.get("JAX_PLATFORMS", "default"),
        "schema": schema,
        "workers": args.workers,
        "result_mb": round(result_mb, 1),
        "inline": inline,
        "spooled_s1": spooled_s1,
        "spooled_s4": spooled_s4,
        "speedup": round(speedup, 2),
        "coord_rss_delta_mb": rss_delta_mb,
        "min_speedup": args.min_speedup,
    }
    if not args.check:
        print(f"  result {result_mb:.1f}MB | drain MB/s: inline "
              f"{drains['inline']} vs spooled x1 {drains['spooled_s1']} "
              f"/ x4 {drains['spooled_s4']} -> {speedup:.2f}x "
              f"(required {args.min_speedup}x); coord RSS saved "
              f"{rss_delta_mb}MB", flush=True)
        if result_mb < 100.0:
            problems.append(f"result only {result_mb:.1f}MB (<100MB): "
                            "raise --sf")
        if speedup < args.min_speedup:
            problems.append(
                f"spooled drain speedup {speedup:.2f}x < "
                f"{args.min_speedup}x")
        # "RSS flat": the spooled coordinator must not pay
        # result-proportional memory — at least half the result size of
        # peak-RSS headroom vs the inline run
        if rss_delta_mb < result_mb / 2:
            problems.append(
                f"coordinator RSS not flat: spooled saved only "
                f"{rss_delta_mb}MB of a {result_mb:.1f}MB result")
        out = args.out or os.path.join(REPO_ROOT, "RESULTS_r01.json")
        result["ok"] = not problems
        with open(out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
        print(f"wrote {out}", flush=True)
    if problems:
        print("FAIL: " + "; ".join(problems), file=sys.stderr)
        return 1
    print("OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
